"""Named-axis collectives — the communication backend over ICI/DCN.

Reference: apex uses torch.distributed/NCCL process-group verbs —
``all_reduce`` (apex/parallel/distributed.py:449-451,
apex/transformer/tensor_parallel/mappings.py:31), ``broadcast``
(distributed.py:253,296), ``all_gather`` (mappings.py:69), batched
``isend/irecv`` (pipeline_parallel/p2p_communication.py:29-67), with CUDA
streams for comm/compute overlap (distributed.py:425-475). SURVEY.md §2.4.

Here each verb is a thin, documented wrapper over the XLA collective that
rides ICI/DCN: process groups become mesh axis names, streams/overlap become
XLA's async-collective latency hiding, and point-to-point pipeline traffic
becomes ``ppermute`` ring shifts. All of these are only meaningful inside a
``shard_map`` (or vmapped/pjitted context) that binds the axis name.

Everything is a tree-map: apex's multi-tensor bucketing (flatten → NCCL →
unflatten, distributed.py:425-475) exists to amortize launch overhead in
eager CUDA; XLA already coalesces collectives, so a pytree maps directly.

Telemetry: every verb runs under a ``comm:<verb>[<axis>]`` named scope
(``apex_tpu.monitor.comms``), so pyprof trace-joins attribute measured comm
seconds per mesh axis and ``monitor.comms.comm_accounting`` tallies payload
bytes per (verb, axis) at trace time. Zero runtime cost: the scope exists
only while tracing.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple, Union

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from apex_tpu.monitor.comms import collective_scope as _comm

AxisNames = Union[str, Tuple[str, ...]]

# ---------------------------------------------------------------------------
# lint introspection hooks (apex_tpu.lint comm-scope rule; read STATICALLY
# via ast.literal_eval, so keep both values plain literals). The prims are
# the data-moving named-axis collectives -- axis_index/axis_size are
# rank/topology queries, not communication; the helpers are the call names
# that satisfy the comm:-scope contract documented above.
# ---------------------------------------------------------------------------

COMM_SCOPE_PRIMS = {"psum", "pmean", "pmax", "pmin", "all_gather",
                    "psum_scatter", "ppermute", "all_to_all", "pshuffle",
                    "all_gather_invariant"}
# Call names that satisfy the comm:-scope contract: the scope helpers
# themselves, plus the conjugate sequence-parallel mappings
# (tensor_parallel/mappings.py) whose forward AND custom-VJP backward each
# run under their own comm: scope — a composite verb built on them needs no
# re-scoping. The quantized wire-dtype collectives (parallel/quantize.py)
# carry their own scopes too: each books its encoded payload AND its fp32
# scale side-channel as separate comm: call sites, so the by-wire-dtype
# accounting (monitor/comms.CommAccount.by_verb_dtype) stays complete.
COMM_SCOPE_HELPERS = ("_comm", "collective_scope",
                      "scatter_to_sequence_parallel_region",
                      "gather_from_sequence_parallel_region",
                      "reduce_scatter_to_sequence_parallel_region",
                      "quantized_reduce_scatter",
                      "quantized_psum_scatter",
                      "quantized_all_gather",
                      "quantized_gather_chunk",
                      "quantized_all_to_all",
                      # two-tier hierarchical collectives
                      # (parallel/hierarchy.py): each hop runs under its
                      # own comm: scope, booked per tier
                      "hier_psum",
                      "hier_pmean",
                      "hier_scatter_chunk",
                      "hier_gather_chunk",
                      "hier_all_to_all")

# The jaxpr-level decomposition contract of sequence parallelism (read
# statically by apex_tpu.lint.trace.sequence_parallel_hazards, like the
# comm-scope sets above): in a sequence-parallel forward trace, activation
# traffic on the TP axis must appear ONLY as these primitives — a bare
# ``psum`` of an activation there means the psum_scatter/all_gather
# decomposition silently regressed to a synchronous all-reduce.
SEQUENCE_PARALLEL_DECOMPOSED_PRIMS = ("reduce_scatter", "all_gather")

# The same contract for the ZeRO optimizer path
# (apex_tpu.lint.trace.zero_redundancy_hazards): in a step whose optimizer
# is sharded over the data axis, BULK gradient traffic there must appear
# only as the reduce-scatter/all-gather conjugate pair
# (optimizers/distributed.py) — a full-size grad ``psum`` on that axis
# means the step still all-reduces what the scatter already reduces.
ZERO_DECOMPOSED_PRIMS = ("reduce_scatter", "all_gather")

# The quantized-collective contract (apex_tpu.lint.trace.
# quantized_comm_hazards, read statically like the sets above): in a step
# that requests a quantized grad reduce (MixedPrecisionOptimizer
# ``reduce_dtype``), BULK reduce traffic on the zero axis must move at a
# 1-byte wire dtype — the encoded ``all_to_all`` pair of
# parallel/quantize.py — with only the tiny fp32 scale side-channel wider.
# A surviving bulk fp32 ``reduce_scatter``/``all_to_all`` payload means the
# quantization silently regressed to the 4 B/elem wire.
QUANTIZED_WIRE_ITEMSIZE = 1
QUANTIZED_REDUCE_PRIMS = ("reduce_scatter", "all_to_all")

# The expert-parallel dispatch contract (apex_tpu.lint.trace.
# moe_dispatch_hazards, read statically like the sets above): a step that
# requests expert parallelism (``GPTConfig.moe_expert_axis``) must move
# its token buckets as ``all_to_all`` over the expert axis — a trace with
# no dispatch all_to_all means the experts silently run replicated; and
# under ``moe_dispatch_dtype`` the DISPATCH-SHAPED payloads (rank >=
# MOE_DISPATCH_MIN_RANK — the (experts, capacity, hidden) buckets, vs the
# rank-2 ZeRO grad-chunk rows that may share the same mesh axis) must
# move at the 1-byte wire dtype (parallel/quantize.quantized_all_to_all).
MOE_DISPATCH_PRIMS = ("all_to_all",)
MOE_DISPATCH_MIN_RANK = 3

#: every verb in this module must run under a ``comm:`` scope; the marker
#: opts the file into the lint rule even if the import shape changes
LINT_COMM_SCOPE = True


def axis_rank(axis: AxisNames) -> jax.Array:
    """This shard's index along ``axis`` (torch.distributed.get_rank(group)
    equivalent, parallel_state.py:263-299)."""
    return lax.axis_index(axis)


def axis_size(axis: AxisNames) -> int:
    """Static size of ``axis`` (get_world_size(group) equivalent)."""
    return lax.axis_size(axis)


def psum(tree: Any, axis: AxisNames) -> Any:
    """All-reduce-sum over a mesh axis (dist.all_reduce SUM)."""
    with _comm("psum", axis, tree):
        return lax.psum(tree, axis)


def pmean(tree: Any, axis: AxisNames) -> Any:
    """Averaging all-reduce — the DDP gradient reduction semantic
    (apex/parallel/distributed.py:449-457: allreduce then divide by
    world size)."""
    with _comm("pmean", axis, tree):
        return lax.pmean(tree, axis)


def pmax(tree: Any, axis: AxisNames) -> Any:
    """All-reduce-max (used by vocab-parallel cross entropy,
    tensor_parallel/cross_entropy.py:30-33, and overflow checks,
    transformer/amp/grad_scaler.py:25-36)."""
    with _comm("pmax", axis, tree):
        return jax.tree.map(lambda x: lax.pmax(x, axis), tree)


def all_gather(tree: Any, axis: AxisNames, *, gather_axis: int = 0, tiled: bool = True) -> Any:
    """Gather shards along ``axis``, concatenating on ``gather_axis``
    (dist.all_gather + cat, tensor_parallel/mappings.py:61-70)."""
    with _comm("all_gather", axis, tree):
        return jax.tree.map(
            lambda x: lax.all_gather(x, axis, axis=gather_axis, tiled=tiled), tree
        )


def reduce_scatter(tree: Any, axis: AxisNames, *, scatter_axis: int = 0) -> Any:
    """Sum-reduce then scatter shards along ``scatter_axis`` — the ZeRO grad
    primitive (contrib DistributedFusedAdam reduce-scatter pipeline,
    distributed_fused_adam.py:397-441)."""
    with _comm("reduce_scatter", axis, tree):
        return jax.tree.map(
            lambda x: lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True),
            tree,
        )


def ppermute_shift(tree: Any, axis: AxisNames, shift: int = 1) -> Any:
    """Ring shift: each shard sends to ``(rank + shift) % size`` — the TPU
    replacement for batched isend/irecv pipeline p2p
    (p2p_communication.py:29-67) and the transport for ring attention."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    with _comm("ppermute", axis, tree):
        return jax.tree.map(lambda x: lax.ppermute(x, axis, perm), tree)


def broadcast(tree: Any, axis: AxisNames, src: int = 0) -> Any:
    """Broadcast ``src``'s shard to all ranks along ``axis``
    (dist.broadcast; tensor_parallel/data.py:50, distributed.py:253)."""

    def _bcast(x):
        # all_gather then static index: XLA lowers this to a broadcast-shaped
        # collective; avoids a host round-trip.
        return lax.all_gather(x, axis, axis=0, tiled=False)[src]

    with _comm("broadcast", axis, tree):
        return jax.tree.map(_bcast, tree)


def all_to_all(
    x: jax.Array, axis: AxisNames, *, split_axis: int, concat_axis: int
) -> jax.Array:
    """All-to-all reshard (basis of Ulysses-style sequence parallelism —
    absent in the reference, SURVEY.md §2.3 row SP)."""
    with _comm("all_to_all", axis, x):
        return lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True)


# ---------------------------------------------------------------------------
# Sharding helpers (host side)
# ---------------------------------------------------------------------------


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def constrain(x: Any, *spec) -> Any:
    """``with_sharding_constraint`` with a PartitionSpec — the GSPMD
    annotation that replaces the reference's hand-written conjugate
    collectives (mappings.py:23-159) in pjit-traced code."""
    return jax.lax.with_sharding_constraint(x, PartitionSpec(*spec))


def shard_map_over(
    mesh: Mesh,
    in_specs,
    out_specs,
    check_vma: bool = False,
) -> Callable[[Callable], Callable]:
    """Decorator sugar for ``jax.shard_map`` over ``mesh``."""

    def deco(fn):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )

    return deco
