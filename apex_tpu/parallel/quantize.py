"""Quantized collectives with per-chunk scales — int8/e5m2 wire dtypes.

Reference: apex's contrib DistributedFusedAdam exposes an e5m2-compressed
allgather (distributed_fused_adam.py:64 ``e5m2_allgather``); the gradient
side of the same idea — quantizing the reduction itself — is EQuARX's
blockwise-quantized all-reduce (PAPERS.md). XLA gives no hook into the
collective's internal hops, so the quantized REDUCTION is emulated at the
jaxpr level as its one-hop decomposition:

    encode rows  --all_to_all(wire dtype)-->  decode --fp32 accumulate

Each rank splits its payload into one row per destination rank, computes a
per-row (per-destination-chunk) fp32 scale, encodes the rows to the 1-byte
wire dtype, and ships them with ``all_to_all``; the scales ride a tiny fp32
side-channel ``all_to_all`` of their own. The receiver decodes each row at
its sender's scale and accumulates in fp32 — so the averaging factor and
the reduction tree stay exact, and only the wire payload is lossy. The
``psum_scatter`` a ZeRO step would issue moves 4 B/elem; the quantized pair
moves 1 B/elem + n fp32 scales (monitor.comms books both at their wire
dtypes — the 1/4-bytes claim is a reported number, not a docstring).

Error feedback (the reason grad quantization converges): the sender keeps
``residual = sent - decode(encode(sent))`` and adds it to the NEXT step's
payload before encoding, so per-destination quantization errors telescope
instead of accumulating — the classic EF/1-bit-Adam construction. The
residual is per-rank state in the SAME flat chunk layout the ZeRO state
uses (this rank's send error for each destination chunk, concatenated);
``amp.MixedPrecisionOptimizer(reduce_dtype=...)`` carries it as one more
tree inside the sharded optimizer state so an overflow-skipped step leaves
it bit-identical per rank (amp/frontend.py). Activations need no residual:
their consumers see fresh values every step, so the per-shard scales alone
bound the error (the ``quantized_all_gather``/``quantized_psum_scatter``
pair under ``GPTConfig.activation_comm_dtype``).

Stochastic rounding (int8 only): adds uniform dither in [-1/2, 1/2) ulp
before rounding, making the per-element error zero-mean — an option on top
of (not a substitute for) the residual.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.monitor.comms import collective_scope as _comm

#: every verb in this module must run under a ``comm:`` scope (the lint
#: comm-scope rule; the marker opts the file in even if imports change)
LINT_COMM_SCOPE = True

#: wire-dtype table: canonical name -> (jnp dtype, max representable
#: magnitude the per-chunk scale normalizes amax to). int8 uses the
#: symmetric [-127, 127] range; e5m2 is jnp.float8_e5m2 (5 exponent /
#: 2 mantissa bits — the reference's compressed-allgather dtype).
WIRE_DTYPES = {
    "int8": (jnp.int8, 127.0),
    "e5m2": (jnp.float8_e5m2, 57344.0),
}


def canon_wire_dtype(dt) -> Optional[str]:
    """Normalize a wire-dtype spec ("int8", "e5m2", jnp.int8,
    jnp.float8_e5m2, None) to its canonical string name."""
    if dt is None:
        return None
    if isinstance(dt, str):
        name = dt.lower()
        if name in ("fp8", "float8_e5m2"):
            name = "e5m2"
    else:
        name = {jnp.dtype(jnp.int8): "int8",
                jnp.dtype(jnp.float8_e5m2): "e5m2"}.get(jnp.dtype(dt))
    if name not in WIRE_DTYPES:
        raise ValueError(
            f"unsupported quantized-collective wire dtype {dt!r}: "
            f"expected one of {sorted(WIRE_DTYPES)}")
    return name


def block_scales(rows: jax.Array, wire_dtype: str) -> jax.Array:
    """Per-row fp32 scales: ``amax(row) / wire_max`` (1.0 for all-zero
    rows, so encode/decode never divides by zero). ``rows`` is ``(n, k)``;
    returns ``(n,)``."""
    _, qmax = WIRE_DTYPES[canon_wire_dtype(wire_dtype)]
    amax = jnp.max(jnp.abs(rows.astype(jnp.float32)), axis=-1)
    return jnp.where(amax > 0, amax / qmax, jnp.ones_like(amax))


def encode(rows: jax.Array, scales: jax.Array, wire_dtype: str,
           key: Optional[jax.Array] = None) -> jax.Array:
    """Encode ``(n, k)`` fp32 rows at their ``(n,)`` per-row scales into
    the wire dtype. ``key`` arms stochastic rounding (int8 only): uniform
    dither in [-1/2, 1/2) ulp before the round, zero-mean per element."""
    wire = canon_wire_dtype(wire_dtype)
    dt, qmax = WIRE_DTYPES[wire]
    scaled = rows.astype(jnp.float32) / scales[..., None]
    if wire == "int8":
        if key is not None:
            scaled = scaled + jax.random.uniform(
                key, scaled.shape, jnp.float32, -0.5, 0.5)
        return jnp.clip(jnp.round(scaled), -qmax, qmax).astype(dt)
    if key is not None:
        raise ValueError("stochastic rounding is int8-only: e5m2's ulp is "
                         "value-dependent, the uniform dither would bias")
    return scaled.astype(dt)


def decode(q: jax.Array, scales: jax.Array,
           dtype: Any = jnp.float32) -> jax.Array:
    """Decode wire-dtype rows back at their per-row scales (fp32 math)."""
    return (q.astype(jnp.float32) * scales[..., None]).astype(dtype)


# ---------------------------------------------------------------------------
# the gradient reduce-scatter (the ZeRO psum_scatter's quantized form)
# ---------------------------------------------------------------------------


def quantized_reduce_scatter(
    x: jax.Array,
    n: int,
    axis: str,
    wire_dtype: str,
    *,
    residual: Optional[jax.Array] = None,
    key: Optional[jax.Array] = None,
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Sum-reduce ``x`` over ``axis`` into this rank's 1-D chunk, moving
    1 B/elem on the wire instead of the fp32 psum_scatter's 4 B.

    The drop-in quantized form of ``optimizers.distributed.scatter_chunk``
    (same flatten/pad/chunk layout, same SUM semantics — callers divide by
    the axis size for gradient averaging). ``residual`` is this rank's
    error-feedback state (flat, ``n * chunk`` long): it is added to the
    payload before encoding and the new residual (payload minus its own
    decode — computable locally, no extra wire) is returned for the caller
    to persist. Pass ``residual=None`` for stateless use (activations,
    censuses). ``key`` arms stochastic rounding (int8 only).

    Returns ``(sum_chunk, new_residual)``; ``new_residual`` is None iff
    ``residual`` was.
    """
    from apex_tpu.optimizers.distributed import _flat_padded

    flat = _flat_padded(x.astype(jnp.float32), n)
    rows = flat.reshape(n, -1)
    if residual is not None:
        rows = rows + residual.reshape(n, -1)
    scales = block_scales(rows, wire_dtype)
    q = encode(rows, scales, wire_dtype, key=key)
    with _comm("all_to_all", axis, q):
        q_recv = lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                                tiled=True)
    with _comm("all_to_all", axis, scales):
        s_recv = lax.all_to_all(scales, axis, split_axis=0, concat_axis=0,
                                tiled=True)
    # decode each received row at ITS SENDER's scale, accumulate in fp32:
    # the reduction itself is exact — only the wire payload was lossy
    chunk = jnp.sum(decode(q_recv, s_recv), axis=0)
    new_residual = None
    if residual is not None:
        new_residual = (rows - decode(q, scales)).reshape(-1)
    return chunk, new_residual


# ---------------------------------------------------------------------------
# activation conjugates (sequence-parallel scatter/gather, mappings.py)
# ---------------------------------------------------------------------------


def _split_blocks(x: jax.Array, n: int, dim: int) -> jax.Array:
    """``(..., n*m, ...) -> (n, ..., m, ...)``: the per-destination block
    axis moved to the front (dim sizes must divide — the SP divisibility
    contract, tensor_parallel/utils.divide)."""
    dim = dim % x.ndim
    m = x.shape[dim] // n
    shaped = x.reshape(x.shape[:dim] + (n, m) + x.shape[dim + 1:])
    return jnp.moveaxis(shaped, dim, 0)


def _merge_blocks(xb: jax.Array, dim: int) -> jax.Array:
    """Inverse of :func:`_split_blocks`: ``(n, ..., m, ...) -> merged``."""
    dim = dim % (xb.ndim - 1)
    moved = jnp.moveaxis(xb, 0, dim)
    return moved.reshape(moved.shape[:dim]
                         + (moved.shape[dim] * moved.shape[dim + 1],)
                         + moved.shape[dim + 2:])


def quantized_psum_scatter(x: jax.Array, axis: str, wire_dtype: str,
                           *, scatter_dim: int) -> jax.Array:
    """``lax.psum_scatter(scatter_dimension=scatter_dim, tiled=True)`` at a
    1-byte wire dtype: per-destination-block scales, all_to_all of the
    encoded blocks + fp32 scale side-channel, decode-then-accumulate. Sum
    semantics and output shape match the fp32 collective exactly; only the
    wire payload is lossy (bounded by the per-block scale). Stateless —
    activation traffic carries no residual (module docstring)."""
    n = lax.axis_size(axis)
    xb = _split_blocks(x.astype(jnp.float32), n, scatter_dim)  # (n, ...)
    flat = xb.reshape(n, -1)
    scales = block_scales(flat, wire_dtype)
    q = encode(flat, scales, wire_dtype).reshape(xb.shape)
    with _comm("all_to_all", axis, q):
        q_recv = lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                                tiled=True)
    with _comm("all_to_all", axis, scales):
        s_recv = lax.all_to_all(scales, axis, split_axis=0, concat_axis=0,
                                tiled=True)
    dec = (q_recv.astype(jnp.float32)
           * s_recv.reshape((n,) + (1,) * (q_recv.ndim - 1)))
    return jnp.sum(dec, axis=0).astype(x.dtype)


def quantized_all_gather(x: jax.Array, axis: str, wire_dtype: str,
                         *, gather_dim: int) -> jax.Array:
    """``lax.all_gather(axis=gather_dim, tiled=True)`` at a 1-byte wire
    dtype: one scale per source shard (fp32 side-channel), decode after the
    gather — each rank reassembles every shard at its sender's scale, so
    all ranks hold the SAME decoded tensor (the replicated-downstream
    convention the SP conjugates rely on is preserved)."""
    n = lax.axis_size(axis)
    xf = x.astype(jnp.float32)
    scales = block_scales(xf.reshape(1, -1), wire_dtype)  # (1,)
    q = encode(xf.reshape(1, -1), scales, wire_dtype).reshape(x.shape)
    with _comm("all_gather", axis, q):
        q_full = lax.all_gather(q, axis, axis=gather_dim, tiled=True)
    with _comm("all_gather", axis, scales):
        s_full = lax.all_gather(scales, axis, axis=0, tiled=True)  # (n,)
    qb = _split_blocks(q_full, n, gather_dim)  # (n, ..., local, ...)
    dec = (qb.astype(jnp.float32)
           * s_full.reshape((n,) + (1,) * (qb.ndim - 1)))
    return _merge_blocks(dec, gather_dim).astype(x.dtype)


# ---------------------------------------------------------------------------
# expert-dispatch conjugate (the MoE all_to_all, transformer/moe.py)
# ---------------------------------------------------------------------------


def _a2a_encoded(x: jax.Array, n: int, axis: str, wire_dtype: str,
                 split_axis: int, concat_axis: int) -> jax.Array:
    """The shared encoded-exchange body: split ``x`` into one block per
    destination rank, encode each at its own fp32 scale, ship blocks +
    scale side-channel with ``all_to_all``, decode each received block at
    ITS SENDER's scale, and merge along ``concat_axis``. Output shape and
    placement match ``lax.all_to_all(tiled=True)`` exactly; only the wire
    payload is lossy (bounded by the per-destination-block scale)."""
    xb = _split_blocks(x.astype(jnp.float32), n, split_axis)  # (n, ...)
    flat = xb.reshape(n, -1)
    scales = block_scales(flat, wire_dtype)
    q = encode(flat, scales, wire_dtype).reshape(xb.shape)
    with _comm("all_to_all", axis, q):
        q_recv = lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                                tiled=True)
    with _comm("all_to_all", axis, scales):
        s_recv = lax.all_to_all(scales, axis, split_axis=0, concat_axis=0,
                                tiled=True)
    dec = (q_recv.astype(jnp.float32)
           * s_recv.reshape((n,) + (1,) * (q_recv.ndim - 1)))
    return _merge_blocks(dec, concat_axis).astype(x.dtype)


def quantized_all_to_all(x: jax.Array, axis: str, wire_dtype: str, *,
                         split_axis: int, concat_axis: int) -> jax.Array:
    """``lax.all_to_all(split_axis=, concat_axis=, tiled=True)`` at a
    1-byte wire dtype — the MoE token dispatch/combine exchange
    (``transformer/moe.py apply_expert_parallel``) quantized like the SP
    activation conjugates: per-destination-shard fp32 scales ride a tiny
    side-channel ``all_to_all`` and the decode happens at the receiver, so
    each expert sees its tokens at their sender's scale. Stateless — the
    dispatched activations are fresh every step, so per-block scales alone
    bound the error and no EF residual is carried (module docstring).

    Differentiable: the backward ships the cotangent through the SAME
    encoded exchange with split/concat swapped (``lax.all_to_all``'s own
    transpose), re-quantized at the cotangent's per-block scales — the
    combine's backward is the dispatch wire and vice versa, so a training
    step moves 1 B/elem in BOTH directions. Like the SP conjugates, the
    custom-VJP backward composes with shard_map but not vmap-of-grad
    (jax's batched tiled all_to_all limitation) — test through shard_map.
    """
    return _qa2a(x, axis, wire_dtype, split_axis, concat_axis)


def _qa2a_impl(x, axis, wire_dtype, split_axis, concat_axis):
    return _a2a_encoded(x, lax.axis_size(axis), axis, wire_dtype,
                        split_axis, concat_axis)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _qa2a(x, axis, wire_dtype, split_axis, concat_axis):
    return _qa2a_impl(x, axis, wire_dtype, split_axis, concat_axis)


def _qa2a_fwd(x, axis, wire_dtype, split_axis, concat_axis):
    return _qa2a_impl(x, axis, wire_dtype, split_axis, concat_axis), None


def _qa2a_bwd(axis, wire_dtype, split_axis, concat_axis, _, g):
    # the transpose of all_to_all(split=s, concat=c) is
    # all_to_all(split=c, concat=s); quantize the cotangent the same way
    return (_qa2a_impl(g, axis, wire_dtype, concat_axis, split_axis),)


_qa2a.defvjp(_qa2a_fwd, _qa2a_bwd)


def quantized_gather_chunk(chunk: jax.Array, axis: str, wire_dtype: str,
                           ) -> jax.Array:
    """All-gather a 1-D ZeRO chunk at a 1-byte wire dtype — the int8 form
    of ``optimizers.distributed.gather_leaf``'s payload compression (the
    reference's e5m2 allgather, distributed_fused_adam.py:64, one notch
    further than bf16). Per-chunk scalar scale, fp32 decode; the fp32
    masters stay exact — every rank sees the same quantized VIEW of the
    updated params, so ranks cannot diverge. Returns the flat fp32 gather
    (callers reshape/cast)."""
    return quantized_all_gather(chunk, axis, wire_dtype, gather_dim=0)
