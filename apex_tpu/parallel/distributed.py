"""Data-parallel gradient reduction (reference: apex/parallel/distributed.py).

The reference's ``DistributedDataParallel`` (:129-639) exists to overlap
bucketed NCCL allreduces with backward compute: per-param grad hooks, bucket
structure discovery in backward order, side streams, flatten/unflatten. Under
XLA none of that machinery is needed — a ``psum`` over the ``data`` mesh axis
inside the jitted step *is* the allreduce, and XLA's latency-hiding scheduler
overlaps it with the backward automatically. What must be preserved are the
**semantics** (SURVEY.md §2.3 row DP):

- gradient *averaging* over the data-parallel group (:449-457);
- ``allreduce_always_fp32``: upcast grads before the reduce (:52-58, buckets
  split by dtype so fp16 grads can be reduced in fp32);
- ``gradient_predivide_factor``: divide by a factor before the reduce and by
  ``world/factor`` after, to keep fp16 sums in range (:167-175, 452-457).

The ``Reducer`` manual variant (:89-126) is the :class:`Reducer` class below
(a thin named wrapper over ``allreduce_gradients`` for custom reduction
timing); ``delay_allreduce`` and bucket knobs are compile-time no-ops here
and intentionally absent.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.monitor.comms import collective_scope as _comm
from apex_tpu.parallel.mesh import AXIS_CONTEXT, AXIS_DATA, AXIS_PIPE

AxisNames = Union[str, Tuple[str, ...]]


def allreduce_gradients(
    grads: Any,
    axes: AxisNames = (AXIS_DATA, AXIS_CONTEXT),
    *,
    allreduce_always_fp32: bool = False,
    gradient_average: bool = True,
    gradient_predivide_factor: float = 1.0,
) -> Any:
    """Average a gradient pytree over the data-parallel mesh axes.

    Call inside ``shard_map``/``pjit`` after ``value_and_grad`` — the moral
    equivalent of apex DDP's bucketed hook pipeline collapsed to one traced
    collective (allreduce_bucket, distributed.py:425-475).
    """
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    world = 1
    for a in axes:
        world *= lax.axis_size(a)
    pre = float(gradient_predivide_factor)

    def _reduce(g):
        dt = g.dtype
        if allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if pre != 1.0:
            g = g / pre
        g = lax.psum(g, axes)
        if gradient_average:
            g = g / (world / pre)
        elif pre != 1.0:
            g = g * pre
        return g.astype(dt)

    # one comm scope + byte tally over the whole grad tree: the DDP
    # reduction is the dominant data-axis traffic, so the trace-join's
    # per-axis comm attribution (monitor/comms.py) must see it
    with _comm("grad_allreduce", axes, grads):
        return jax.tree.map(_reduce, grads)


def allreduce_gradients_by_spec(
    grads: Any,
    specs: Any,
    *,
    data_axes: AxisNames = (AXIS_DATA, AXIS_CONTEXT),
    replicated_axes: Sequence[str] = (AXIS_PIPE,),
    zero_axis: Optional[str] = None,
    **opts,
) -> Any:
    """Spec-aware gradient reduction for hybrid-parallel training.

    Grads average over the data axes their param is *replicated* on — an
    axis appearing in the param's PartitionSpec means each shard holds a
    **different** slice (e.g. MoE experts sharded over ``data``), whose
    gradient is already complete locally and must NOT be mixed across
    shards. Grads of params replicated over an axis in ``replicated_axes``
    (the axis does not appear in their PartitionSpec) are additionally
    **summed** over it. Under the SPMD pipeline this is exactly the
    reference's embedding-group allreduce for tied embeddings
    (parallel_state.py:165-184): stage-masked contributions (input
    embedding on the first stage, LM head on the last) sum to the total
    tied gradient.

    ``zero_axis`` drops that axis from ``data_axes``: with a ZeRO-sharded
    optimizer (``amp.MixedPrecisionOptimizer(zero_axis=...)``) the
    optimizer's psum_scatter IS the reduction over it — same averaging
    factor — and a second all-reduce here would double-count (the
    ``lint.trace.zero_redundancy_hazards`` tripwire). Every other axis
    (context partial-grads, pipe embedding ties) still reduces here.
    """
    data_axes = (data_axes,) if isinstance(data_axes, str) else tuple(data_axes)
    if zero_axis is not None:
        data_axes = tuple(a for a in data_axes if a != zero_axis)

    def _reduce(g, spec):
        spec_axes = set()
        for entry in spec:
            if entry is None:
                continue
            spec_axes.update((entry,) if isinstance(entry, str) else entry)
        reduce_axes = tuple(a for a in data_axes if a not in spec_axes)
        if reduce_axes:
            g = allreduce_gradients(g, reduce_axes, **opts)
        skipped = tuple(a for a in data_axes if a in spec_axes)
        if skipped and opts.get("gradient_average", True):
            # the loss is a mean of per-shard local means; a data-sharded
            # param's AD gradient sums every shard's cotangent (e.g. MoE
            # expert weights receive tokens from all shards via the
            # all_to_all transpose), so the 1/axis-size averaging factor
            # still applies even though no psum happens
            denom = 1
            for a in skipped:
                denom *= lax.axis_size(a)
            g = g / denom
        extra = tuple(a for a in replicated_axes if a not in spec_axes)
        if extra:
            with _comm("psum", extra, g):
                g = lax.psum(g, extra)
        return g

    from jax.sharding import PartitionSpec

    return jax.tree.map(
        _reduce, grads, specs,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )


class DistributedDataParallel:
    """Thin functional counterpart of apex.parallel.DistributedDataParallel.

    Wraps a loss function so its gradients come back already averaged over
    the DP axes; parameter "broadcast at construction" (distributed.py:253)
    is a non-event because SPMD params are replicated by sharding.

    >>> ddp = DistributedDataParallel(loss_fn, allreduce_always_fp32=True)
    >>> loss, grads = ddp.value_and_grad(params, batch)   # inside shard_map
    """

    def __init__(
        self,
        loss_fn,
        axes: AxisNames = (AXIS_DATA, AXIS_CONTEXT),
        *,
        allreduce_always_fp32: bool = False,
        gradient_average: bool = True,
        gradient_predivide_factor: float = 1.0,
    ):
        self.loss_fn = loss_fn
        self.axes = axes
        self.opts = dict(
            allreduce_always_fp32=allreduce_always_fp32,
            gradient_average=gradient_average,
            gradient_predivide_factor=gradient_predivide_factor,
        )

    def value_and_grad(self, params, *args, **kwargs):
        loss, grads = jax.value_and_grad(self.loss_fn)(params, *args, **kwargs)
        return loss, allreduce_gradients(grads, self.axes, **self.opts)


class Reducer:
    """Manually-triggered gradient (or param) averaging — the lightweight
    alternative to DDP (apex/parallel/distributed.py:89-126: "allreduce is
    done manually via <reducer>.reduce(); useful for custom update
    intervals").

    >>> red = Reducer()
    >>> grads = accumulate(...)       # any number of local steps
    >>> grads = red.reduce(grads)     # inside shard_map, when you choose
    """

    def __init__(
        self,
        axes: AxisNames = (AXIS_DATA, AXIS_CONTEXT),
        *,
        gradient_average: bool = True,
        allreduce_always_fp32: bool = False,
        gradient_predivide_factor: float = 1.0,
    ):
        self.axes = (axes,) if isinstance(axes, str) else tuple(axes)
        self.opts = dict(
            gradient_average=gradient_average,
            allreduce_always_fp32=allreduce_always_fp32,
            gradient_predivide_factor=gradient_predivide_factor,
        )

    def reduce(self, tree: Any) -> Any:
        return allreduce_gradients(tree, self.axes, **self.opts)
