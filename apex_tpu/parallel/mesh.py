"""Model-parallel topology as a named device mesh — the TPU-native "MPU".

Reference: apex/transformer/parallel_state.py:57-184 builds NCCL process
groups for data/tensor/pipeline/model/embedding parallelism from
``(tp_size, pp_size, virtual_pp_size, pp_split_rank)`` and records the
calling rank's position in each. On TPU there are no process groups: a
single ``jax.sharding.Mesh`` with named axes carries the whole topology, and
every "group" becomes a mesh axis name passed to a collective.

Topology contract preserved from the reference (parallel_state.py:119-184):

- tensor-parallel ranks are **contiguous** device blocks (``:142-149``) —
  here the ``model`` axis is the fastest-varying mesh dimension, so TP
  collectives ride the fastest ICI links ("adjacent ranks share a box",
  ``:83-86``);
- data-parallel ranks stride by tp_size within a pipeline block
  (``:119-131``) — the ``data`` axis varies next;
- pipeline-parallel ranks stride widest (``:159-164``) — the ``pipe`` axis is
  slowest-varying, matching PP's tolerance for higher-latency links (DCN);
- the ``context`` axis (sequence/ring parallelism — absent in the reference,
  SURVEY.md §2.3) sits between ``data`` and ``model`` so ring-attention
  ppermutes stay on fast links.

Flattened device order is therefore ``pipe → data → context → model`` with
``model`` innermost; ``rank_coords`` exposes the inverse map for tests that
verify parity with the reference's rank arithmetic.

Virtual-pipeline (interleaved schedule) state mirrors
parallel_state.py:367-382; embedding-group membership (first + last + optional
split stage, ``:165-184``) is exposed as stage predicates rather than a
process group — weight-tying grad reduction happens inside the pipeline
schedule (see apex_tpu.transformer.pipeline_parallel).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_PIPE = "pipe"
AXIS_DATA = "data"
AXIS_CONTEXT = "context"
AXIS_MODEL = "model"
#: the inter-island (multi-host) axis of the two-tier topology
#: (parallel/hierarchy.py): slowest-varying of all, so island-mates stay
#: contiguous on fast ICI links and only this axis crosses the modeled
#: DCN tier. Present only when ``islands > 1`` is requested.
AXIS_DCN = "dcn"

#: Canonical axis order, slowest- to fastest-varying across the device list.
MESH_AXIS_NAMES: Tuple[str, ...] = (AXIS_PIPE, AXIS_DATA, AXIS_CONTEXT, AXIS_MODEL)
#: Axis order of a two-tier (island) mesh: ``dcn`` outermost.
POD_AXIS_NAMES: Tuple[str, ...] = (AXIS_DCN,) + MESH_AXIS_NAMES


@dataclasses.dataclass
class _ParallelState:
    """Module-global topology record (the reference keeps ~15 globals,
    parallel_state.py:24-54; one dataclass is easier to destroy/inspect)."""

    mesh: Optional[Mesh] = None
    virtual_pipeline_world_size: Optional[int] = None
    virtual_pipeline_rank: Optional[int] = None
    pipeline_split_rank: Optional[int] = None


_STATE = _ParallelState()


def initialize_model_parallel(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_split_rank: Optional[int] = None,
    context_parallel_size: int = 1,
    islands: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build and install the global mesh (parallel_state.py:57-184 equivalent).

    The data-parallel size is inferred as
    ``n_devices // (tp * cp * pp)``, mirroring the reference's
    ``world_size % (tp * pp) == 0`` sanity check (``:88-94``).

    Args:
      tensor_model_parallel_size: size of the ``model`` axis.
      pipeline_model_parallel_size: size of the ``pipe`` axis.
      virtual_pipeline_model_parallel_size: number of interleaved model chunks
        per pipeline stage (reference ``:104-111``).
      pipeline_model_parallel_split_rank: stage where the encoder/decoder
        split sits, for T5-style models (reference ``:96-102,165-184``).
      context_parallel_size: size of the ``context`` (sequence) axis — a new
        capability relative to the reference.
      islands: number of ICI islands (modeled hosts) — ``islands > 1``
        prepends a ``dcn`` axis (slowest-varying, so island-mates stay
        contiguous on fast links) carrying the inter-host tier of the
        two-tier topology (parallel/hierarchy.py). The data-parallel
        size is then the PER-ISLAND size: global data parallelism is
        ``islands * dp``.
      devices: explicit device list; defaults to ``jax.devices()``.

    Returns:
      The installed ``jax.sharding.Mesh``.
    """
    tp = int(tensor_model_parallel_size)
    pp = int(pipeline_model_parallel_size)
    cp = int(context_parallel_size)
    isl = int(islands)
    devs = list(devices) if devices is not None else jax.devices()
    world_size = len(devs)
    denom = tp * pp * cp * isl
    if world_size % denom != 0:
        raise RuntimeError(
            f"world size ({world_size}) is not divisible by tensor parallel "
            f"size ({tp}) x pipeline parallel size ({pp}) x context parallel "
            f"size ({cp})" + (f" x islands ({isl})" if isl > 1 else "")
        )
    dp = world_size // denom
    if virtual_pipeline_model_parallel_size is not None and pp < 2:
        raise RuntimeError(
            "pipeline-model-parallel size should be greater than 1 with "
            "interleaved schedule"
        )

    if isl > 1:
        grid = np.asarray(devs, dtype=object).reshape(isl, pp, dp, cp, tp)
        mesh = Mesh(grid, POD_AXIS_NAMES)
    else:
        grid = np.asarray(devs, dtype=object).reshape(pp, dp, cp, tp)
        mesh = Mesh(grid, MESH_AXIS_NAMES)

    _STATE.mesh = mesh
    _STATE.virtual_pipeline_world_size = virtual_pipeline_model_parallel_size
    _STATE.virtual_pipeline_rank = (
        0 if virtual_pipeline_model_parallel_size is not None else None
    )
    _STATE.pipeline_split_rank = pipeline_model_parallel_split_rank
    return mesh


def model_parallel_is_initialized() -> bool:
    """parallel_state.py:198-203 equivalent."""
    return _STATE.mesh is not None


def get_mesh() -> Mesh:
    if _STATE.mesh is None:
        raise RuntimeError(
            "model parallel mesh is not initialized "
            "(call apex_tpu.parallel.initialize_model_parallel first)"
        )
    return _STATE.mesh


def destroy_model_parallel() -> None:
    """parallel_state.py:428-453 equivalent."""
    _STATE.mesh = None
    _STATE.virtual_pipeline_world_size = None
    _STATE.virtual_pipeline_rank = None
    _STATE.pipeline_split_rank = None


# ---------------------------------------------------------------------------
# World sizes (static — known from the mesh shape).
# Ranks are *per-device* values: inside shard_map use
# collectives.axis_rank(axis); these module-level getters cover host-side
# schedule construction, where the reference queried torch.distributed
# (parallel_state.py:205-425).
# ---------------------------------------------------------------------------


def _axis_size(name: str) -> int:
    return get_mesh().shape[name]


def get_tensor_model_parallel_world_size() -> int:
    return _axis_size(AXIS_MODEL)


def get_pipeline_model_parallel_world_size() -> int:
    return _axis_size(AXIS_PIPE)


def get_data_parallel_world_size() -> int:
    return _axis_size(AXIS_DATA)


def get_context_parallel_world_size() -> int:
    return _axis_size(AXIS_CONTEXT)


def get_island_world_size() -> int:
    """Number of ICI islands (the ``dcn`` axis size; 1 on a flat mesh)."""
    mesh = get_mesh()
    return mesh.shape[AXIS_DCN] if AXIS_DCN in mesh.axis_names else 1


def get_data_parallel_axes() -> Tuple[str, ...]:
    """Mesh axes the batch shards over: ``("dcn", "data")`` on a two-tier
    island mesh (global data parallelism spans both), ``("data",)``
    otherwise — the spec for batch sharding and for the bulk-grad group
    the hierarchical collectives decompose (parallel/hierarchy.py)."""
    if AXIS_DCN in get_mesh().axis_names:
        return (AXIS_DCN, AXIS_DATA)
    return (AXIS_DATA,)


def get_gradient_reduction_axes() -> Tuple[str, ...]:
    """Mesh axes over which parameter gradients must be averaged.

    With context parallelism each sequence shard produces partial gradients
    for the *full* parameter set, so grad reduction spans ``data`` and
    ``context`` (the reference's data-parallel group, distributed.py:449-451,
    covers only ``data`` because CP does not exist there). On a two-tier
    island mesh the ``dcn`` axis joins the group — but a BULK reduce must
    not bind it flat together with another axis
    (lint.trace.flat_dcn_collective_hazards): decompose hierarchically."""
    return get_data_parallel_axes() + (AXIS_CONTEXT,)


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    return _STATE.pipeline_split_rank


def get_rank_info_str() -> str:
    """Topology suffix for log records and journal lines.

    The reference formats a per-process (dp, tp, pp, vpp) rank tuple into
    every log record (apex/transformer/log_util.py); under single-process
    SPMD a process holds EVERY rank, so the honest per-process equivalent
    is the mesh topology itself. ``utils.log_util.RankInfoFilter`` and
    ``monitor.journal`` both consume this; empty when no mesh is installed.
    """
    if _STATE.mesh is None:
        return ""
    pp, dp, cp, tp = (_STATE.mesh.shape[a] for a in MESH_AXIS_NAMES)
    isl = (_STATE.mesh.shape[AXIS_DCN]
           if AXIS_DCN in _STATE.mesh.axis_names else 1)
    vpp = _STATE.virtual_pipeline_world_size
    return (f" mesh({f'dcn{isl} ' if isl > 1 else ''}pp{pp} dp{dp} cp{cp} "
            f"tp{tp}{f' vpp{vpp}' if vpp else ''})")


# -- virtual pipeline (interleaved schedule) state --------------------------
# Mirrors parallel_state.py:367-382: the schedule sets the current model
# chunk index while building/running the interleaved 1F1B loop.


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _STATE.virtual_pipeline_world_size


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    return _STATE.virtual_pipeline_rank


def set_virtual_pipeline_model_parallel_rank(rank: Optional[int]) -> None:
    _STATE.virtual_pipeline_rank = rank


# ---------------------------------------------------------------------------
# Stage predicates (host-side, per pipeline stage index).
# The reference's is_pipeline_{first,last}_stage consult the calling rank
# (parallel_state.py:308-330); in SPMD form the pipeline schedule iterates
# stages explicitly, so these take the stage index as an argument.
# ---------------------------------------------------------------------------


def is_pipeline_first_stage(stage: int, ignore_virtual: bool = False) -> bool:
    if not ignore_virtual and _STATE.virtual_pipeline_world_size is not None:
        if _STATE.virtual_pipeline_rank != 0:
            return False
    return stage == 0


def is_pipeline_last_stage(stage: int, ignore_virtual: bool = False) -> bool:
    if not ignore_virtual and _STATE.virtual_pipeline_world_size is not None:
        if _STATE.virtual_pipeline_rank != _STATE.virtual_pipeline_world_size - 1:
            return False
    return stage == get_pipeline_model_parallel_world_size() - 1


def embedding_stages() -> List[int]:
    """Pipeline stages holding (tied) embedding weights: first + last
    (+ encoder/decoder split), reference parallel_state.py:165-184."""
    pp = get_pipeline_model_parallel_world_size()
    stages = [0]
    split = _STATE.pipeline_split_rank
    if split is not None and split not in stages:
        stages.append(split)
    if pp - 1 not in stages:
        stages.append(pp - 1)
    return stages


# ---------------------------------------------------------------------------
# Rank arithmetic parity helpers
# ---------------------------------------------------------------------------


def rank_coords(flat_rank: int) -> Tuple[int, int, int, int]:
    """Map a flat device index to ``(pipe, data, context, model)`` coords.

    Inverse of the flattened mesh order; lets tests assert the reference's
    rank→group contract: TP contiguous (parallel_state.py:142-149), DP
    striding by tp within a pipe block (:119-131), PP striding widest
    (:159-164)."""
    mesh = get_mesh()
    pp, dp, cp, tp = (mesh.shape[a] for a in MESH_AXIS_NAMES)
    if not 0 <= flat_rank < pp * dp * cp * tp:
        raise ValueError(f"rank {flat_rank} out of range")
    m = flat_rank % tp
    c = (flat_rank // tp) % cp
    d = (flat_rank // (tp * cp)) % dp
    p = flat_rank // (tp * cp * dp)
    return (p, d, c, m)


def make_virtual_mesh(
    n_devices: int,
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    context_parallel_size: int = 1,
    **kwargs,
) -> Mesh:
    """Convenience for tests/dry-runs: initialize over the first
    ``n_devices`` of ``jax.devices()`` (virtual CPU devices in CI)."""
    devs = jax.devices()
    if len(devs) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(devs)} "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N)"
        )
    return initialize_model_parallel(
        tensor_model_parallel_size=tensor_model_parallel_size,
        pipeline_model_parallel_size=pipeline_model_parallel_size,
        context_parallel_size=context_parallel_size,
        devices=devs[:n_devices],
        **kwargs,
    )
