"""Multi-host launch glue (reference: apex/parallel/multiproc.py:1-36).

The reference's launcher spawns ``world_size`` local processes with ``--rank
i`` env plumbing (pre-``torchrun``). On TPU pods the runtime launches one
process per host and the coordination layer is ``jax.distributed``;
:func:`initialize_distributed` wraps it with the same env-driven UX
(MASTER_ADDR/RANK/WORLD_SIZE names kept for reference-script migration, with
the JAX names honored too). On a single host it is a no-op, so scripts are
launcher-agnostic like apex examples run with or without
``torch.distributed.launch``.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize multi-host JAX if a multi-process env is configured.

    Resolution order: explicit args → JAX env (``JAX_COORDINATOR_ADDRESS``…)
    → torch-style env (``MASTER_ADDR``/``MASTER_PORT``/``WORLD_SIZE``/
    ``RANK``, the variables apex's launcher exports, multiproc.py:20-437).
    Returns True when distributed init ran, False for single-process."""
    env = os.environ
    coordinator_address = (
        coordinator_address
        or env.get("JAX_COORDINATOR_ADDRESS")
        or (
            f"{env['MASTER_ADDR']}:{env.get('MASTER_PORT', '1234')}"
            if "MASTER_ADDR" in env
            else None
        )
    )
    num_processes = num_processes or int(
        env.get("JAX_NUM_PROCESSES", env.get("WORLD_SIZE", "1"))
    )
    process_id = (
        process_id
        if process_id is not None
        else int(env.get("JAX_PROCESS_ID", env.get("RANK", "0")))
    )
    if num_processes <= 1:
        return False
    if coordinator_address is None:
        raise RuntimeError(
            f"WORLD_SIZE/JAX_NUM_PROCESSES={num_processes} but no coordinator "
            "address (set MASTER_ADDR[:MASTER_PORT] or JAX_COORDINATOR_ADDRESS)"
        )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def local_rank() -> int:
    """The LOCAL_RANK the apex launcher exports (multiproc.py:31-35).

    Without the env var the TPU runtime runs one process per host, whose
    node-local rank is 0 (jax.process_index() is the *global* rank — wrong
    for per-node resource selection)."""
    return int(os.environ.get("LOCAL_RANK", "0"))
