"""apex_tpu.parallel — mesh topology, collectives, and the data-parallel runtime.

Reference: apex/parallel/ (DDP, SyncBatchNorm, LARC, multiproc) and
apex/transformer/parallel_state.py (the "MPU"). Here both layers share one
substrate: a named ``jax.sharding.Mesh`` whose axes replace NCCL process
groups, and XLA collectives that replace bucketed allreduce.
"""

from apex_tpu.parallel.mesh import (  # noqa: F401
    AXIS_CONTEXT,
    AXIS_DATA,
    AXIS_MODEL,
    AXIS_PIPE,
    destroy_model_parallel,
    get_context_parallel_world_size,
    get_data_parallel_world_size,
    get_gradient_reduction_axes,
    get_mesh,
    get_pipeline_model_parallel_split_rank,
    get_pipeline_model_parallel_world_size,
    get_tensor_model_parallel_world_size,
    get_virtual_pipeline_model_parallel_rank,
    get_virtual_pipeline_model_parallel_world_size,
    initialize_model_parallel,
    model_parallel_is_initialized,
    rank_coords,
    set_virtual_pipeline_model_parallel_rank,
)
from apex_tpu.parallel import collectives  # noqa: F401
from apex_tpu.parallel.sync_batchnorm import (  # noqa: F401
    SyncBatchNorm,
    convert_syncbn_model,
    sync_batch_norm,
    sync_moments,
)
