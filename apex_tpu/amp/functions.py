"""User-registered precision-cast wrappers — the O1 decorator surface
(reference: apex/amp/amp.py:29-64 ``register_half_function`` /
``register_float_function`` / ``register_promote_function`` and the
``half_function``/``float_function``/``promote_function`` decorators).

The reference monkey-patches modules at ``amp.init`` time; under tracing, a
wrapper applied at call sites is the honest equivalent: it casts floating
array args to the target dtype on entry. Policies with a cast model (O2/O3)
make these wrappers no-ops for half functions (the network already runs in
compute dtype), matching the reference where the O1 patcher is only
installed when ``patch_torch_functions`` is set.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu import precision as _precision

# Module-level active policy, set by amp.initialize (the _amp_state analog).
_active_policy: Optional[_precision.Policy] = None


def set_active_policy(policy: Optional[_precision.Policy]) -> None:
    global _active_policy
    _active_policy = policy


class disable_casts:
    """Context manager suspending the registered-function casts
    (``amp.disable_casts``, apex/amp/handle.py:163-167 — e.g. around an op
    that must see its inputs untouched)."""

    def __enter__(self):
        global _active_policy
        self._saved = _active_policy
        _active_policy = None
        return self

    def __exit__(self, *exc):
        global _active_policy
        _active_policy = self._saved
        return False


def _cast_floats(args, kwargs, dtype):
    def _c(a):
        # real floating only — casting complex would drop imaginary parts
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating):
            return a.astype(dtype)
        return a

    return jax.tree.map(_c, (args, kwargs))


def half_function(fn: Callable) -> Callable:
    """Run ``fn`` in the policy's compute dtype (FP16-whitelist;
    amp.py:38-41, the MLP module registers itself this way, mlp.py:24)."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        p = _active_policy
        # Active only for uncast-model policies (O1): with a cast model
        # (O2/O3) the wrapper is a no-op so deliberately-fp32 leaves (e.g.
        # keep_batchnorm_fp32 params) pass through untouched.
        if (
            p is None
            or p.cast_model_type is not None
            or p.compute_dtype == jnp.float32
        ):
            return fn(*args, **kwargs)
        args, kwargs = _cast_floats(args, kwargs, p.compute_dtype)
        return fn(*args, **kwargs)

    return wrapped


def float_function(fn: Callable) -> Callable:
    """Run ``fn`` in fp32 (FP32-blacklist: losses, norms, exp/log families;
    amp.py:43-46)."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if _active_policy is None:
            return fn(*args, **kwargs)
        args, kwargs = _cast_floats(args, kwargs, jnp.float32)
        return fn(*args, **kwargs)

    return wrapped


def promote_function(fn: Callable) -> Callable:
    """Promote all floating args to the widest floating dtype present
    (multi-arg type promotion; amp.py:48-51, torch_overrides.py:86-115)."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if _active_policy is None:
            return fn(*args, **kwargs)
        leaves = jax.tree.leaves((args, kwargs))
        dts = [a.dtype for a in leaves
               if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.inexact)]
        if not dts:
            return fn(*args, **kwargs)
        widest = functools.reduce(jnp.promote_types, dts)
        args, kwargs = _cast_floats(args, kwargs, widest)
        return fn(*args, **kwargs)

    return wrapped
