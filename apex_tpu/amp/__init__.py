"""apex_tpu.amp — mixed precision with O0–O3 policies and loss scaling.

Reference: apex/amp/ (SURVEY.md §2.1). See frontend.py for the design mapping.
"""

from apex_tpu.precision import Policy, get_policy, cast_params, upcast_params  # noqa: F401
from apex_tpu.amp.scaler import LossScaler, check_overflow  # noqa: F401
from apex_tpu.amp.frontend import (  # noqa: F401
    AmpTrainState,
    MixedPrecisionOptimizer,
    MPOptState,
    Zero3Setup,
    initialize,
)
from apex_tpu.amp.functions import (  # noqa: F401
    disable_casts,
    float_function,
    half_function,
    promote_function,
    set_active_policy,
)
