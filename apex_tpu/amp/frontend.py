"""amp frontend — initialize, mixed-precision optimizer, train state.

The TPU-native re-design of apex.amp's user surface:

- ``initialize(params, optimizer, opt_level=..., **overrides)`` mirrors
  ``apex.amp.initialize`` (reference: apex/amp/frontend.py:195-358 +
  _initialize.py:145-263): casts params per policy, wraps the optimizer with
  master weights + loss scaling + overflow skip.
- ``MixedPrecisionOptimizer`` replaces the reference's in-place optimizer
  surgery (_process_optimizer.py:321-489: ``_amp_stash`` master clones, patched
  ``step``/``zero_grad``, pre/post-backward hooks). In functional JAX all of
  that state is an explicit pytree and "patching step" is a ``lax.cond``.
- ``AmpTrainState`` is the convenience bundle (flax TrainState analog) used by
  the examples.

What has no analog and why: O1's namespace monkey-patching
(apex/amp/amp.py:68-177) casts call sites at runtime; under tracing, casts are
explicit in the model code, so O1 here means "params fp32, compute bf16" via
policy-aware modules (see apex_tpu.precision.Policy.op_dtype).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu import precision as _precision
from apex_tpu.amp.scaler import LossScaler
from apex_tpu.ops.multi_tensor import tree_l2norm, tree_scale
from apex_tpu.optimizers._common import ClassOptimizer, sharded_tree_sumsq


class MPOptState(NamedTuple):
    """Optimizer + amp carried state.

    ``master`` holds fp32 master weights when the policy asks for them
    (the ``_amp_stash`` fp32_from_fp16 groups of _process_optimizer.py:28-90);
    otherwise None. ``inner`` is the wrapped transform's state, always built
    over the fp32 view of params. ``scaler`` is the loss-scale state machine.

    Under ``zero_axis`` (the ZeRO path, contrib distributed_fused_adam.py
    semantics) ``master`` is ALWAYS present and holds this rank's 1-D fp32
    chunk tree (1/n of every leaf); ``inner`` is built over the chunks, so
    the whole optimizer footprint is 1/n per rank.

    ``residual`` (None unless ``reduce_dtype`` arms the quantized grad
    reduce-scatter) is the error-feedback state riding the sharded trees:
    ``{"err": <tree of flat fp32 leaves in the chunk layout — this rank's
    send error per destination chunk, concatenated>[, "key": <per-rank
    PRNG key when stochastic rounding is armed>]}``. Like masters and
    moments it is per-rank state behind the universal chunk specs, and an
    overflow-skipped step leaves it bit-identical on every rank.
    """

    inner: Any
    master: Any
    scaler: LossScaler
    residual: Any = None


class Zero3Setup(NamedTuple):
    """Host-side wiring bundle for fully-sharded (ZeRO-3) training, built
    by :meth:`MixedPrecisionOptimizer.zero3_init`.

    ``params`` is the persistent working-param CHUNK tree (each leaf this
    rank's 1/n slice, in the model dtype): the bf16 params are never
    materialized whole — layers all-gather just-in-time inside the layer
    loop (models/_transformer.run_layers ``chunk_meta``) and free after
    use. ``param_specs``/``state_specs`` are the shard_map in/out specs for
    the chunk trees; ``meta`` (optimizers.distributed.ChunkedMeta) carries
    the static local full shapes the JIT gathers rebuild."""

    params: Any
    param_specs: Any
    opt_state: Any
    state_specs: Any
    meta: Any


def _spec_axis_names(entry):
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, (tuple, list)) else (entry,)


def _canon_gather_dtype(dt):
    if dt is None:
        return None
    if isinstance(dt, str):
        low = dt.lower()
        if low in ("bf16", "bfloat16"):
            return jnp.dtype(jnp.bfloat16)
        if low in ("e5m2", "fp8", "float8_e5m2"):
            # the reference's e5m2-compressed allgather spelling: a bare
            # cast-and-gather at 1 B/elem (no scales — the float8 dynamic
            # range carries the value; use "int8" for the scaled wire)
            return jnp.dtype(jnp.float8_e5m2)
        if low == "int8":
            # quantized param gather: per-chunk fp32 scale side-channel,
            # decode after the collective (parallel/quantize.py;
            # optimizers.distributed.gather_leaf routes on the int dtype)
            return jnp.dtype(jnp.int8)
    canon = jnp.dtype(dt)
    if jnp.issubdtype(canon, jnp.integer) and canon != jnp.dtype(jnp.int8):
        # the only integer wire is the quantized int8 path — a wider int
        # would silently route through the 8-bit encode (gather_leaf
        # dispatches on integer-ness), delivering less precision than
        # the name promises
        raise ValueError(
            f"unsupported integer gather_dtype {dt!r}: the quantized "
            f"param-gather wire is 'int8' only (parallel/quantize.py); "
            f"use 'int8', 'bf16', or a float dtype")
    return canon


def _scaler_from_policy(policy: _precision.Policy, **scaler_kwargs) -> LossScaler:
    return LossScaler.create(loss_scale=policy.loss_scale, **scaler_kwargs)


class MixedPrecisionOptimizer:
    """Wraps an optax transform with amp semantics.

    Per step (cf. the reference's scale_loss exit path, handle.py:107-154, and
    patched step, _process_optimizer.py:353-364):

    1. unscale grads by 1/loss_scale into fp32, detecting non-finites;
    2. all-reduce of found_inf is the caller's job when running under a mesh
       (see apex_tpu.transformer.amp.MeshGradScaler);
    3. ``lax.cond(found_inf)``: skip (state unchanged) or apply the inner
       update to the fp32 master params;
    4. cast masters back to the model dtypes (multi_tensor_scale copy-out,
       _process_optimizer.py:14-25);
    5. scaler.update(found_inf).

    ``zero_axis`` switches steps 3-4 to the ZeRO path (the first-class
    spelling of ``optimizers.distributed``'s contrib
    DistributedFusedAdam/LAMB math): masters + inner state live as 1/n
    fp32 chunks, the grads arrive UNREDUCED over that axis (psum_scatter
    performs the reduction), and the updated params come back through a
    (optionally bf16-compressed) all-gather. See :meth:`zero_init`.
    """

    def __init__(
        self,
        optimizer: Union[optax.GradientTransformation, ClassOptimizer],
        policy: _precision.Policy,
        log_grad_norm: bool = False,
        log_group_norms: bool = False,
        zero_axis: Optional[str] = None,
        zero_level: int = 2,
        dcn_axis: Optional[str] = None,
        dcn_wire: Optional[str] = "int8",
        gather_dtype: Optional[Any] = None,
        reduce_dtype: Optional[str] = None,
        stochastic_rounding: bool = False,
        stacked_keys: Tuple[str, ...] = ("layers",),
        **scaler_kwargs,
    ):
        self.inner = (
            optimizer.transform if isinstance(optimizer, ClassOptimizer) else optimizer
        )
        self.policy = policy
        #: mesh axis the fp32 masters + inner optimizer state are ZeRO-
        #: sharded over (optimizers/distributed.py math: psum_scatter of
        #: the UNREDUCED grads is the data-parallel reduction, then a
        #: sharded inner step over 1/n chunks, then an all-gather of the
        #: updated params). init/apply_gradients must then run inside
        #: shard_map binding the axis — see :meth:`zero_init`. At levels
        #: 1/2 params SHARDED over the axis (MoE experts with
        #: ``moe_expert_axis`` == the zero axis) compose: their masters
        #: and moments stay the local expert shard (already 1/n of the
        #: leaf — Xu et al.'s weight-update sharding per parameter group),
        #: their grads skip the psum_scatter (the all_to_all transpose
        #: already summed every shard's cotangents) but keep the 1/n
        #: averaging, and no post-update gather touches them. Level 3
        #: still requires every param replicated over the axis (the chunk
        #: drive has no expert-shard story).
        self.zero_axis = zero_axis
        #: bool tree over the model params (True on leaves SHARDED over
        #: ``zero_axis`` — expert leaves); None until the ZeRO wiring
        #: (``zero_abstract_state``/``zero_init``) reads the param specs,
        #: which also fills ``_zero_expert_specs`` (local shape -> the
        #: param's own PartitionSpec, for the sharded state's out-specs).
        self._zero_sharded = None
        self._zero_expert_specs = None
        #: ZeRO stage under ``zero_axis``. 1/2 (one implementation here:
        #: masters AND moments always shard together) keep the bf16 working
        #: params replicated and all-gather them after every update. 3
        #: shards the *model* too: the working params persist as chunk
        #: trees (see :meth:`zero3_init`), each layer's weights are
        #: all-gathered just-in-time inside the layer loop (and re-gathered
        #: in backward via per-layer remat), grads arrive as per-layer
        #: reduce-scattered chunks (the JIT gather's AD transpose), and
        #: ``apply_gradients`` skips the post-update bulk gather entirely —
        #: the updated chunks ARE the persistent state.
        self.zero_level = int(zero_level)
        if self.zero_level not in (1, 2, 3):
            raise ValueError(f"zero_level must be 1, 2 or 3, got {zero_level}")
        if self.zero_level >= 3 and zero_axis is None:
            raise ValueError("zero_level=3 requires zero_axis (the mesh axis "
                             "the params shard over)")
        #: top-level param-dict keys holding scan-stacked layer trees
        #: (leading num_layers dim): under ``zero_level=3`` these chunk
        #: PER ROW — ``(L, ...) -> (L, k)`` — so one layer gathers at a
        #: time (optimizers.distributed.local_chunk_stacked).
        self.stacked_keys = tuple(stacked_keys)
        #: wire dtype of the updated-param all-gather under ``zero_axis``
        #: (the reference's e5m2-compressed allgather knob,
        #: distributed_fused_adam.py:64): "bf16" halves the gather bytes.
        #: fp32 masters stay exact — only the broadcast payload is cast,
        #: so the params every rank sees are the bf16-rounded view of the
        #: masters (free under O2, opt-in precision trade elsewhere).
        self.gather_dtype = _canon_gather_dtype(gather_dtype)
        if self.gather_dtype is not None and zero_axis is None:
            raise ValueError("gather_dtype only applies with zero_axis set "
                             "(it is the ZeRO param-gather wire dtype)")
        if (self.gather_dtype is not None and self.zero_level >= 3
                and jnp.issubdtype(self.gather_dtype, jnp.integer)):
            raise ValueError(
                "gather_dtype='int8' does not compose with zero_level=3: "
                "the ZeRO-3 per-layer gathers sit INSIDE the differentiated "
                "region and the int8 encode's round() would zero the "
                "gradients flowing through its AD transpose — quantize the "
                "level-1/2 post-update gather, or use 'bf16' for the JIT "
                "gathers")
        #: wire dtype of the GRADIENT reduce-scatter under ``zero_axis``
        #: ("int8" | "e5m2"): the fp32 psum_scatter becomes the quantized
        #: all_to_all decode-then-accumulate pair (parallel/quantize.py) —
        #: 1 B/elem on the wire plus a tiny fp32 per-chunk scale
        #: side-channel — with a sender-side error-feedback residual
        #: carried in :class:`MPOptState.residual` so quantization errors
        #: telescope instead of accumulating. The decode-accumulate and
        #: the /n averaging stay exact fp32. Memory note: the residual is
        #: per-rank fp32 state at the FULL (padded) leaf size — the
        #: standard EF/1-bit-Adam trade of state bytes for wire bytes;
        #: arm it when the interconnect, not HBM, is the bottleneck.
        from apex_tpu.parallel.quantize import canon_wire_dtype

        self.reduce_dtype = canon_wire_dtype(reduce_dtype)
        if self.reduce_dtype is not None and zero_axis is None:
            raise ValueError("reduce_dtype only applies with zero_axis set "
                             "(it is the ZeRO grad reduce-scatter wire "
                             "dtype)")
        if self.reduce_dtype is not None and self.zero_level >= 3:
            raise ValueError(
                "reduce_dtype does not compose with zero_level=3 yet: the "
                "ZeRO-3 grads reduce-scatter inside the per-layer gather "
                "transposes (optimizers.distributed.gather_leaf AD), not "
                "in apply_gradients — quantize at level 1/2, or use "
                "gather_dtype for the JIT gathers")
        #: mesh axis of the slow inter-island (DCN) tier under ZeRO
        #: (parallel/hierarchy.py): with ``dcn_axis`` set the masters and
        #: moments shard over the COMBINED ``(dcn_axis, zero_axis)`` group
        #: — flat chunk index of rank ``(d, i)`` is ``d * n_ici + i`` —
        #: and every bulk collective runs hierarchically: intra-island
        #: reduce/gather on the fast ICI links, exactly ONE
        #: ``1/n_ici``-sized exchange across DCN. ``dcn_wire`` (default
        #: "int8" — EQuARX's deployment point: quantize exactly where the
        #: slow tier binds) moves the inter-island GRAD hop at 1 B/elem
        #: with the same error-feedback residual contract as
        #: ``reduce_dtype`` (the intra-island stage stays exact fp32);
        #: ``dcn_wire=None`` keeps the whole decomposition exact —
        #: bit-identical, values AND grads, to the flat tuple-axis
        #: collectives (tests/test_hierarchy.py pins it).
        self.dcn_axis = dcn_axis
        self.dcn_wire = canon_wire_dtype(dcn_wire) if dcn_axis else None
        if dcn_axis is not None:
            if zero_axis is None:
                raise ValueError(
                    "dcn_axis only applies with zero_axis set: it names "
                    "the slow tier of the hierarchical ZeRO collectives "
                    "(parallel/hierarchy.py)")
            if self.zero_level >= 3:
                raise ValueError(
                    "dcn_axis does not compose with zero_level=3: the "
                    "per-layer JIT gathers ride the single-axis chunk "
                    "drive (models/_transformer.run_layers) — shard the "
                    "working params over the island-internal axis and "
                    "keep dcn for the optimizer tiers at levels 1/2")
            if self.reduce_dtype is not None:
                raise ValueError(
                    "reduce_dtype does not compose with dcn_axis: the "
                    "grad wire is per TIER there — dcn_wire quantizes "
                    "the inter-island hop while the intra-island stage "
                    "stays exact fp32 (quantizing the fast links buys "
                    "nothing, EQuARX's observation)")
            from apex_tpu.monitor.comms import register_dcn_axis

            register_dcn_axis(dcn_axis)
        #: int8-only uniform dither before the round (zero-mean per-element
        #: error) — an option on top of, not a substitute for, the
        #: error-feedback residual. Carries a per-rank PRNG key in
        #: ``MPOptState.residual["key"]``.
        self.stochastic_rounding = bool(stochastic_rounding)
        if self.stochastic_rounding and self.reduce_dtype != "int8":
            raise ValueError("stochastic_rounding requires "
                             "reduce_dtype='int8' (e5m2's ulp is value-"
                             "dependent; None has nothing to round)")
        #: when True, ``apply_gradients`` metrics include the global L2 norm
        #: of the unscaled grads — the journal hook (monitor/journal.py).
        #: Off by default: the extra tree reduction, while small next to the
        #: step's matmuls, must be opt-in so uninstrumented programs stay
        #: byte-identical.
        self.log_grad_norm = bool(log_grad_norm)
        #: when True, metrics also carry ``grad_norm_by_group`` — the L2
        #: norm per top-level parameter group (monitor/diagnose.py's
        #: overflow-forensics breakdown: a group whose norm is non-finite
        #: names the first non-finite layer from the journal alone). Same
        #: opt-in byte-identity contract as ``log_grad_norm``.
        self.log_group_norms = bool(log_group_norms)
        #: per-leaf tuples of mesh axes each param is SHARDED over (from
        #: the param_specs seen by ``zero_abstract_state``/``zero_init``):
        #: the norm metrics psum over ``zero_axis`` plus these, so
        #: tp/pp-hybrid shards count once and replicated leaves are not
        #: double-counted. None until the ZeRO wiring runs.
        self._zero_norm_axes = None
        self._scaler_kwargs = scaler_kwargs

    def _stacked_tree(self, params) -> Any:
        """Bool tree: True on leaves under a ``stacked_keys`` top-level
        entry (scan-stacked layer params) — only consulted at
        ``zero_level=3``, where those leaves chunk per row."""
        if self.zero_level < 3 or not isinstance(params, dict):
            return jax.tree.map(lambda _: False, params)
        return {k: jax.tree.map(lambda _: k in self.stacked_keys, v)
                for k, v in params.items()}

    def _sharded_tree(self, params) -> Any:
        """Bool tree: True on leaves SHARDED over the zero axis (expert
        leaves — recorded by the ZeRO wiring from the param specs); all
        False when no wiring ran (dense models, ad-hoc test harnesses)."""
        if self._zero_sharded is None:
            return jax.tree.map(lambda _: False, params)
        return self._zero_sharded

    def _zero_group(self) -> Tuple[str, ...]:
        """The mesh axes the optimizer state shards over: ``(dcn, zero)``
        on a two-tier mesh — lax tuple-axis order, first name most
        significant, matching the hier_* chunk layout — else
        ``(zero,)``."""
        if self.dcn_axis is not None:
            return (self.dcn_axis, self.zero_axis)
        return (self.zero_axis,)

    def _zero_group_size(self):
        """Total shard count across the group (n_dcn * n_ici)."""
        n = lax.axis_size(self.zero_axis)
        if self.dcn_axis is not None:
            n = n * lax.axis_size(self.dcn_axis)
        return n

    def _zero_group_index(self):
        """This rank's flat chunk index in the group:
        ``d * n_ici + i`` (hierarchy.py's equivalence contract)."""
        idx = lax.axis_index(self.zero_axis)
        if self.dcn_axis is not None:
            idx = (lax.axis_index(self.dcn_axis)
                   * lax.axis_size(self.zero_axis) + idx)
        return idx

    def _chunk_tree(self, params, dtype=None):
        """This rank's per-leaf ZeRO state: a 1-D chunk of every
        zero-axis-REPLICATED leaf (stacked-aware at level 3); leaves
        SHARDED over the zero axis (expert params, levels 1/2) pass
        through as their local shard — already 1/n of the global leaf.
        Must run inside shard_map (or an axis_env trace) binding the
        zero axis (and ``dcn_axis`` when set — the chunk index flattens
        the two-tier group)."""
        from apex_tpu.optimizers.distributed import (
            local_chunk,
            local_chunk_stacked,
        )

        n = self._zero_group_size()
        idx = self._zero_group_index()

        def chunk(p, st, sh):
            if dtype is not None:
                p = p.astype(dtype)
            if sh:
                return p
            return (local_chunk_stacked if st else local_chunk)(p, n, idx)

        return jax.tree.map(chunk, params, self._stacked_tree(params),
                            self._sharded_tree(params))

    def _init_residual(self, model_params):
        """The error-feedback state for the quantized grad reduce-scatter
        (None when ``reduce_dtype`` is unset, so the state structure —
        and every ``reduce_dtype=None`` trace — is bit-identical to the
        unquantized path). Must run inside shard_map (or an axis_env
        trace) binding the zero axis, like :meth:`init`."""
        if self.reduce_dtype is None and self.dcn_wire is None:
            return None
        from apex_tpu.optimizers.distributed import chunk_size

        n = self._zero_group_size()
        # the residual covers the QUANTIZED wire only: the flat quantized
        # reduce sends to all n ranks; the hierarchical form quantizes
        # just the DCN hop, whose payload is n_dcn chunks (the island-
        # reduced rows) — 1/n_ici the flat residual's bytes
        wire_ranks = (lax.axis_size(self.dcn_axis)
                      if self.dcn_wire is not None else n)
        # zero-axis-SHARDED leaves (MoE experts) have no reduce wire —
        # their grads never leave the rank — so they carry an EMPTY
        # residual leaf (structure preserved, zero bytes)
        err = jax.tree.map(
            lambda p, sh: jnp.zeros(
                (0,) if sh else (chunk_size(p.size, n) * wire_ranks,),
                jnp.float32),
            model_params, self._sharded_tree(model_params))
        residual = {"err": err}
        if self.stochastic_rounding:
            # per-rank dither stream: senders round independently
            residual["key"] = jax.random.fold_in(
                jax.random.PRNGKey(0), lax.axis_index(self.zero_axis))
        return residual

    def zero3_shard(self, model_params) -> Any:
        """The persistent ZeRO-3 working-param chunk tree (model dtypes):
        stacked layer leaves become ``(L, k)`` per-row chunks, everything
        else a 1-D chunk. Traced counterpart of :meth:`zero3_init`'s
        placement — also usable directly under an ``axis_env`` trace
        (the evidence censuses)."""
        if self.zero_level < 3:
            raise ValueError("zero3_shard requires zero_level=3")
        return self._chunk_tree(model_params)

    def init(self, model_params) -> MPOptState:
        if self.zero_axis is not None:
            # ZeRO: keep only this rank's fp32 chunk of every leaf — the
            # chunks ARE the masters (exact fp32 regardless of
            # policy.master_weights: without them the sharded update could
            # not be applied without re-gathering params first). Must run
            # inside shard_map binding the axis (zero_init wraps this).
            # At zero_level=3 the masters mirror the working-param chunk
            # layout (per-row chunks for stacked layer leaves) so the
            # sharded update consumes the per-layer-scattered grads as-is.
            master = self._chunk_tree(model_params, dtype=jnp.float32)
            return MPOptState(
                inner=self.inner.init(master),
                master=master,
                scaler=_scaler_from_policy(self.policy, **self._scaler_kwargs),
                residual=self._init_residual(model_params),
            )
        if self.policy.master_weights:
            master = _precision.upcast_params(model_params)
        else:
            master = None
        inner = self.inner.init(master if master is not None else model_params)
        return MPOptState(
            inner=inner,
            master=master,
            scaler=_scaler_from_policy(self.policy, **self._scaler_kwargs),
        )

    def scale_loss(self, loss: jax.Array, state: MPOptState) -> jax.Array:
        """``with amp.scale_loss(...)`` enter path (handle.py:113)."""
        return state.scaler.scale(loss)

    def apply_gradients(
        self,
        state: MPOptState,
        model_params,
        scaled_grads,
        *,
        found_inf_reducer: Optional[Callable[[jax.Array], jax.Array]] = None,
        **update_kwargs,
    ):
        """Returns ``(new_model_params, new_state, metrics)``.

        ``scaled_grads`` are grads of the *scaled* loss w.r.t. model params.
        ``found_inf_reducer`` lets callers all-reduce the overflow flag across
        a mesh axis (the model-parallel reduction of
        apex/transformer/amp/grad_scaler.py:25-36).

        Under ``zero_axis``, ``scaled_grads`` must be the *unreduced*
        local-mean grads — the psum_scatter IS the data-axis reduction
        (same 1/n averaging factor as ``allreduce_gradients``); reduce over
        every OTHER grad axis (context/pipe ties) before calling. The
        overflow flag is pmax'd over the zero axis internally so the
        sharded state stays bit-identical on every rank through a skipped
        step; pass ``found_inf_reducer`` for the model/pipe axes as usual.

        Under ``zero_level=3`` both ``model_params`` and ``scaled_grads``
        are CHUNK trees: the grads arrive already reduce-scattered over
        the zero axis (each JIT layer gather's AD transpose is a per-layer
        psum_scatter — sum semantics, so the 1/n averaging still happens
        here), the sharded update runs directly on the chunks, and no
        gather follows: the new bf16 chunks (cast from the stepped
        masters) ARE the returned model params.
        """
        grads32, found_inf = state.scaler.unscale(scaled_grads, out_dtype=jnp.float32)
        if self.zero_axis is not None:
            from apex_tpu.parallel import collectives as _coll

            # each rank unscaled a DIFFERENT local grad: the skip decision
            # must agree along the shard axis (the whole two-tier group
            # when dcn_axis is set) or the chunks diverge
            found_inf = _coll.pmax(
                found_inf.astype(jnp.float32),
                self._zero_group() if self.dcn_axis is not None
                else self.zero_axis) > 0
        if found_inf_reducer is not None:
            found_inf = found_inf_reducer(found_inf)

        if self.zero_axis is not None:
            if self.zero_level >= 3:
                return self._apply_zero3(
                    state, model_params, grads32, found_inf, update_kwargs)
            return self._apply_zero(
                state, model_params, grads32, found_inf, update_kwargs)

        step_params = state.master if state.master is not None else model_params

        def _do_step(operand):
            params, inner_state = operand
            updates, new_inner = self.inner.update(
                grads32, inner_state, params, **update_kwargs
            )
            new_params = optax.apply_updates(params, updates)
            return new_params, new_inner

        def _skip_step(operand):
            return operand

        new_step_params, new_inner = jax.lax.cond(
            found_inf, _skip_step, _do_step, (step_params, state.inner)
        )

        if state.master is not None:
            # master -> model copy-out in the model dtypes.
            new_model = jax.tree.map(
                lambda mp, p: mp.astype(p.dtype), new_step_params, model_params
            )
            new_master = new_step_params
        else:
            new_model = new_step_params
            new_master = None

        new_scaler = state.scaler.update(found_inf)
        metrics = {
            "found_inf": found_inf,
            "loss_scale": new_scaler.loss_scale,
        }
        if self.log_grad_norm:
            # fp16_utils.FP16_Optimizer.step reports this unconditionally;
            # here it rides the metrics dict only when asked for
            metrics["grad_norm"] = tree_l2norm(grads32)
        if self.log_group_norms:
            from apex_tpu.monitor.diagnose import group_grad_norms

            metrics["grad_norm_by_group"] = group_grad_norms(grads32)
        return new_model, MPOptState(new_inner, new_master, new_scaler), metrics

    # -- the ZeRO step (contrib distributed_fused_adam.py:397-477 math) -----
    def _apply_zero(self, state, model_params, grads32, found_inf,
                    update_kwargs):
        """Sharded step: scatter → inner update on chunks → compressed
        gather. Collectives run UNCONDITIONALLY (uniform SPMD schedule —
        a collective inside a cond branch is a lowering hazard), so the
        overflow skip is a select back to the old chunks: the discarded
        update's non-finites never touch state, and since ``found_inf`` is
        axis-consistent every rank selects the same way — a skipped step
        leaves the sharded state bit-identical on every rank."""
        from apex_tpu.optimizers.distributed import gather_leaf, scatter_chunk

        axis = self.zero_axis
        n = self._zero_group_size()
        sharded = self._sharded_tree(grads32)
        new_residual = state.residual
        if self.dcn_axis is not None:
            # two-tier path (parallel/hierarchy.py): the scatter factors
            # into intra-island psum_scatter -> ONE inter-island exchange
            # of the 1/n_ici shard — exact fp32 when dcn_wire is None
            # (bit-identical to the flat tuple-axis scatter), or the
            # 1-byte quantized wire with the error-feedback residual
            # telescoping across steps. Sharded (expert) leaves pass
            # through with their empty residual, as on the flat path.
            from apex_tpu.parallel.hierarchy import hier_scatter_chunk

            dcn = self.dcn_axis
            if self.dcn_wire is not None:
                err_tree = state.residual["err"]
                leaves, treedef = jax.tree.flatten(grads32)
                err_leaves = treedef.flatten_up_to(err_tree)
                sh_leaves = treedef.flatten_up_to(sharded)
                pairs = [(g, e) if sh else hier_scatter_chunk(
                    g, dcn, axis, wire_dtype=self.dcn_wire, residual=e)
                    for g, e, sh in zip(leaves, err_leaves, sh_leaves)]
                g_chunks = treedef.unflatten([c / n for c, _ in pairs])
                new_residual = {"err": treedef.unflatten(
                    [e for _, e in pairs])}
            else:
                g_chunks = jax.tree.map(
                    lambda g, sh: (g if sh else hier_scatter_chunk(
                        g, dcn, axis)[0]) / n,
                    grads32, sharded)
        elif self.reduce_dtype is not None:
            # quantized reduce-scatter (parallel/quantize.py): encoded
            # all_to_all + fp32 decode-then-accumulate — SUM semantics
            # identical to scatter_chunk, 1 B/elem on the wire. The
            # error-feedback residual compensates next step's payload;
            # its update is selected back on overflow below, with the
            # masters, so a skipped step leaves it bit-identical per rank.
            # Zero-axis-SHARDED leaves (MoE experts) have no wire at all:
            # their grads arrive complete (the dispatch all_to_all
            # transpose summed every shard's cotangents) and pass through
            # with their empty residual leaf untouched.
            from apex_tpu.parallel.quantize import quantized_reduce_scatter

            err_tree = state.residual["err"]
            key = state.residual.get("key")
            leaves, treedef = jax.tree.flatten(grads32)
            err_leaves = treedef.flatten_up_to(err_tree)
            sh_leaves = treedef.flatten_up_to(sharded)
            if key is not None:
                new_key, *subkeys = jax.random.split(key, len(leaves) + 1)
            else:
                new_key, subkeys = None, [None] * len(leaves)
            pairs = [(g, e) if sh else quantized_reduce_scatter(
                g, n, axis, self.reduce_dtype, residual=e, key=k)
                for g, e, k, sh in zip(leaves, err_leaves, subkeys,
                                       sh_leaves)]
            g_chunks = treedef.unflatten([c / n for c, _ in pairs])
            stepped_err = treedef.unflatten([e for _, e in pairs])
            new_residual = {"err": stepped_err}
            if new_key is not None:
                # the key advances unconditionally (it is a dither stream,
                # not model state): ranks stay in lockstep through skips
                new_residual["key"] = new_key
        else:
            # the scatter IS the data-axis gradient reduction; /n is the
            # same averaging factor allreduce_gradients applies. Sharded
            # (expert) leaves skip the scatter — their grad is already
            # this rank's complete shard — but keep the averaging factor
            # (the allreduce_gradients_by_spec convention).
            g_chunks = jax.tree.map(
                lambda g, sh: (g if sh else scatter_chunk(g, n, axis)) / n,
                grads32, sharded)

        updates, stepped_inner = self.inner.update(
            g_chunks, state.inner, state.master, **update_kwargs)
        stepped_master = optax.apply_updates(state.master, updates)
        keep = lambda new, old: jax.tree.map(  # noqa: E731
            lambda a, b: jnp.where(found_inf, b, a), new, old)
        new_master = keep(stepped_master, state.master)
        new_inner = keep(stepped_inner, state.inner)
        if self.reduce_dtype is not None or self.dcn_wire is not None:
            new_residual = dict(
                new_residual,
                err=keep(new_residual["err"], state.residual["err"]))

        # all-gather the updated params; with gather_dtype the payload is
        # compressed on the wire, then stored back in each param's dtype.
        # On the two-tier mesh the gather decomposes too: ONE 1/n_ici-
        # sized inter-island hop, then the intra-island rebuild — same
        # bits as the flat gather (the payload is cast exactly once).
        # Sharded (expert) leaves never gather: the stepped local master
        # IS the new local shard — just the dtype copy-out.
        if self.dcn_axis is not None:
            from apex_tpu.parallel.hierarchy import hier_gather_chunk

            def _gather(c, p):
                return hier_gather_chunk(
                    c, p.shape, p.dtype, self.dcn_axis, axis,
                    gather_dtype=self.gather_dtype)
        else:
            def _gather(c, p):
                return gather_leaf(c, p.shape, p.dtype, axis,
                                   gather_dtype=self.gather_dtype)
        new_model = jax.tree.map(
            lambda c, p, sh: c.astype(p.dtype) if sh else _gather(c, p),
            new_master, model_params, sharded)

        new_scaler = state.scaler.update(found_inf)
        metrics = {
            "found_inf": found_inf,
            "loss_scale": new_scaler.loss_scale,
        }
        if self.log_grad_norm:
            # norm of the REDUCED gradient, from this rank's chunks: the
            # per-leaf shard-psum (the whole zero group + the param's own
            # sharded axes) reproduces tree_l2norm on the full tree under
            # hybrid meshes too (chunk padding contributes exact zeros)
            metrics["grad_norm"] = jnp.sqrt(sharded_tree_sumsq(
                g_chunks, self._zero_group(), self._zero_norm_axes))
        if self.log_group_norms:
            from apex_tpu.monitor.diagnose import group_grad_norms

            metrics["grad_norm_by_group"] = group_grad_norms(
                g_chunks, psum_axis=self._zero_group(),
                extra_axes=self._zero_norm_axes)
        return (new_model,
                MPOptState(new_inner, new_master, new_scaler, new_residual),
                metrics)

    # -- the ZeRO-3 step: no scatter (grads arrive as chunks), no gather ----
    def _apply_zero3(self, state, param_chunks, grads32, found_inf,
                     update_kwargs):
        """Fully-sharded step: the grads were reduce-scattered layer by
        layer in the backward (gather transposes), so the update is pure
        per-chunk arithmetic — inner step on the fp32 master chunks,
        overflow select back to the old chunks (axis-consistent, so a
        skipped step leaves every rank's shard bit-identical), then the
        new working params are the bf16-cast of the new masters. Zero
        collectives: the PR-5 bulk post-update all-gather is gone —
        updated chunks are already the persistent state."""
        axis = self.zero_axis
        n = lax.axis_size(axis)
        # the gather transposes SUMMED over the axis; /n is the same
        # averaging factor allreduce_gradients applies
        g_chunks = jax.tree.map(lambda g: g / n, grads32)

        updates, stepped_inner = self.inner.update(
            g_chunks, state.inner, state.master, **update_kwargs)
        stepped_master = optax.apply_updates(state.master, updates)
        keep = lambda new, old: jax.tree.map(  # noqa: E731
            lambda a, b: jnp.where(found_inf, b, a), new, old)
        new_master = keep(stepped_master, state.master)
        new_inner = keep(stepped_inner, state.inner)

        # master -> model copy-out in the model dtypes, chunk for chunk
        new_params = jax.tree.map(
            lambda m, c: m.astype(c.dtype), new_master, param_chunks)

        new_scaler = state.scaler.update(found_inf)
        metrics = {
            "found_inf": found_inf,
            "loss_scale": new_scaler.loss_scale,
        }
        if self.log_grad_norm:
            metrics["grad_norm"] = jnp.sqrt(sharded_tree_sumsq(
                g_chunks, axis, self._zero_norm_axes))
        if self.log_group_norms:
            from apex_tpu.monitor.diagnose import group_grad_norms

            metrics["grad_norm_by_group"] = group_grad_norms(
                g_chunks, psum_axis=axis,
                extra_axes=self._zero_norm_axes)
        return new_params, MPOptState(new_inner, new_master, new_scaler), metrics

    # -- ZeRO wiring helpers (host side) ------------------------------------
    def zero_abstract_state(self, model_params, mesh, param_specs=None):
        """Per-device ShapeDtypeStruct tree of the ZeRO :class:`MPOptState`.

        Built WITHOUT binding the mesh axes (the chicken-and-egg of
        shard_map out_specs): each leaf's local shape is derived from its
        PartitionSpec (sharded dims divide by their axis sizes), then the
        1-D fp32 chunk is 1/n of that, and the chunk tree is fed through
        the real ``inner.init`` under ``eval_shape`` so arbitrarily nested
        inner states come out with the right structure."""
        from apex_tpu.optimizers.distributed import chunk_size

        if self.zero_axis is None:
            raise ValueError("zero_abstract_state requires zero_axis")
        n = mesh.shape[self.zero_axis]
        if self.dcn_axis is not None:
            # two-tier: chunks shard over the COMBINED (dcn, zero) group
            n *= mesh.shape[self.dcn_axis]
        leaves, treedef = jax.tree.flatten(model_params)
        if param_specs is None:
            spec_leaves = [None] * len(leaves)
        else:
            spec_leaves = jax.tree.leaves(
                param_specs, is_leaf=lambda x: isinstance(x, P))
            if len(spec_leaves) != len(leaves):
                raise ValueError(
                    f"param_specs tree has {len(spec_leaves)} specs for "
                    f"{len(leaves)} params")

        def leaf_struct(p, spec):
            """(state struct, sharded-over-zero-axis) for one param: the
            1-D fp32 chunk for zero-axis-REPLICATED leaves, the fp32
            LOCAL shard for zero-axis-sharded (expert) leaves — Xu et
            al.'s weight-update sharding applied per parameter group."""
            shape = list(p.shape)
            over_zero = False
            for d, entry in enumerate(spec or ()):
                for ax in _spec_axis_names(entry):
                    if ax == self.zero_axis:
                        over_zero = True
                    if self.dcn_axis is not None and ax in (
                            self.zero_axis, self.dcn_axis):
                        raise ValueError(
                            f"param of shape {tuple(p.shape)} is sharded "
                            f"over {ax!r}: the two-tier optimizer "
                            f"(dcn_axis) requires every param replicated "
                            f"over BOTH group axes — expert-axis-sharded "
                            f"MoE params compose with the single-tier "
                            f"zero_axis only (their grads never cross "
                            f"the island boundary the hierarchical "
                            f"reduction covers)")
                    shape[d] //= mesh.shape[ax]
            if over_zero:
                if len(shape) < 2:
                    raise ValueError(
                        f"param of shape {tuple(p.shape)} is sharded over "
                        f"the zero axis {self.zero_axis!r} with a 1-D "
                        f"local shard: the sharded-state specs classify "
                        f"1-D leaves as chunks, so rank-1 expert leaves "
                        f"are unsupported — stack them (E, 1) or keep "
                        f"them replicated")
                return jax.ShapeDtypeStruct(tuple(shape), jnp.float32), True
            size = 1
            for s in shape:
                size *= s
            return (jax.ShapeDtypeStruct((chunk_size(size, n),),
                                         jnp.float32), False)

        def sharded_axes(spec):
            out = []
            for entry in (spec or ()):
                if entry is None:
                    continue
                for ax in (entry if isinstance(entry, (tuple, list))
                           else (entry,)):
                    if ax not in out:
                        out.append(ax)
            return tuple(out)

        self._zero_norm_axes = treedef.unflatten(
            [sharded_axes(s) for s in spec_leaves])
        structs, flags = zip(*[leaf_struct(p, s)
                               for p, s in zip(leaves, spec_leaves)])
        self._zero_sharded = treedef.unflatten(list(flags))
        expert_specs: dict = {}
        for st, sp, fl in zip(structs, spec_leaves, flags):
            if not fl:
                continue
            prev = expert_specs.get(st.shape)
            if prev is not None and prev != sp:
                raise ValueError(
                    f"two zero-axis-sharded params share the local shape "
                    f"{st.shape} but carry different specs ({prev} vs "
                    f"{sp}): the shape-keyed sharded-state specs cannot "
                    f"disambiguate them")
            expert_specs[st.shape] = sp
        self._zero_expert_specs = expert_specs
        chunks = treedef.unflatten(list(structs))
        scaler = _scaler_from_policy(self.policy, **self._scaler_kwargs)
        residual = None
        if self.reduce_dtype is not None or self.dcn_wire is not None:
            # error-feedback state: per-rank flat fp32 leaves in the chunk
            # layout (one chunk per QUANTIZED-wire destination — all n for
            # the flat reduce, n_dcn for the hierarchical DCN hop),
            # mirroring _init_residual exactly; sharded (expert) leaves
            # have no wire and carry an empty leaf
            wire_ranks = (mesh.shape[self.dcn_axis]
                          if self.dcn_wire is not None else n)
            residual = {"err": treedef.unflatten([
                jax.ShapeDtypeStruct(
                    (0,) if fl else (st.shape[0] * wire_ranks,),
                    jnp.float32)
                for st, fl in zip(structs, flags)])}
            if self.stochastic_rounding:
                residual["key"] = jax.ShapeDtypeStruct((2,), jnp.uint32)

        def fake_init(c):
            return MPOptState(inner=self.inner.init(c), master=c,
                              scaler=scaler)

        # residual structs attach AFTER eval_shape: they are already
        # abstract (ShapeDtypeStructs), not closure constants to trace
        return jax.eval_shape(fake_init, chunks)._replace(residual=residual)

    def zero_state_specs(self, state, mesh):
        """shard_map specs for a ZeRO :class:`MPOptState` (or its abstract
        shapes): chunk leaves (1-D) carry the universal per-device spec
        ``P(tuple(mesh.axis_names))`` — each device owns exactly its chunk,
        with no replication assumption over ANY axis, so chunks of model-
        and pipe-sharded params round-trip correctly too; scalars (step
        counters, the loss-scale machine) are replicated. Zero-axis-SHARDED
        (expert) leaves — whose masters/moments are the fp32 LOCAL shard,
        rank >= 2 by construction — carry their param's own PartitionSpec,
        matched by local shape (``zero_abstract_state`` records the
        table and rejects ambiguous shapes)."""
        from apex_tpu.optimizers.distributed import state_specs as _specs

        base = _specs(state, tuple(mesh.axis_names))
        expert = self._zero_expert_specs
        if not expert:
            return base
        return jax.tree.map(
            lambda x, sp: expert.get(
                tuple(getattr(x, "shape", ()) or ()), sp),
            state, base)

    def zero_init(self, model_params, mesh, param_specs):
        """Initialize the sharded state from host-side (global) params.

        Returns ``(opt_state, state_specs)``; thread ``state_specs``
        through the train step's shard_map in/out specs. ``param_specs``
        is the params' PartitionSpec tree (the same one the step uses).
        """
        if self.zero_level >= 3:
            raise ValueError("zero_level=3 shards the params themselves; "
                             "wire with zero3_init (returns the chunked "
                             "param tree + specs + gather metadata)")
        abstract = self.zero_abstract_state(model_params, mesh, param_specs)
        sspecs = self.zero_state_specs(abstract, mesh)
        init = jax.jit(jax.shard_map(
            self.init, mesh=mesh, in_specs=(param_specs,),
            out_specs=sspecs, check_vma=False))
        return init(model_params), sspecs

    # -- ZeRO-3 wiring (host side) ------------------------------------------
    def _zero3_local_shapes(self, model_params, mesh, param_specs):
        """Per-leaf LOCAL (per-device) full shapes: each dim divided by the
        sizes of the mesh axes its PartitionSpec shards it over — what a
        JIT gather must rebuild inside shard_map. Also validates that no
        param is sharded over the zero axis (the level-1/2 constraint,
        unchanged) and records ``_zero_norm_axes``."""
        leaves, treedef = jax.tree.flatten(model_params)
        if param_specs is None:
            spec_leaves = [None] * len(leaves)
        else:
            spec_leaves = jax.tree.leaves(
                param_specs, is_leaf=lambda x: isinstance(x, P))
            if len(spec_leaves) != len(leaves):
                raise ValueError(
                    f"param_specs tree has {len(spec_leaves)} specs for "
                    f"{len(leaves)} params")

        def local_shape(p, spec):
            shape = [int(d) for d in p.shape]
            for d, entry in enumerate(spec or ()):
                for ax in _spec_axis_names(entry):
                    if ax == self.zero_axis:
                        raise ValueError(
                            f"param of shape {tuple(p.shape)} is SHARDED "
                            f"over the zero axis {self.zero_axis!r} — "
                            f"zero_level=3 requires every param replicated "
                            f"over it (expert-axis-sharded MoE params "
                            f"compose at ZeRO levels 1/2 only: the chunk "
                            f"drive has no expert-shard gather story)")
                    if mesh is not None:
                        shape[d] //= mesh.shape[ax]
            return tuple(shape)

        def sharded_axes(spec):
            out = []
            for entry in (spec or ()):
                for ax in _spec_axis_names(entry):
                    if ax not in out:
                        out.append(ax)
            return tuple(out)

        self._zero_norm_axes = treedef.unflatten(
            [sharded_axes(s) for s in spec_leaves])
        shapes = treedef.unflatten(
            [local_shape(p, s) for p, s in zip(leaves, spec_leaves)])
        return shapes, treedef, spec_leaves

    def zero3_meta(self, model_params, mesh=None, param_specs=None):
        """The static gather metadata (optimizers.distributed.ChunkedMeta)
        for a ZeRO-3 chunk tree of ``model_params``: per-leaf LOCAL full
        ``ShapeDtypeStruct``s — the per-LAYER row shape for stacked layer
        leaves — plus the axis and wire dtype. Without ``mesh`` the global
        shapes are used (axis_env traces, serial censuses)."""
        shapes, treedef, _ = self._zero3_local_shapes(
            model_params, mesh, param_specs)
        return self._zero3_meta_from(
            model_params, shapes, self._stacked_tree(model_params))

    def _zero3_meta_from(self, model_params, shapes, stacked):
        """ChunkedMeta from precomputed local shapes (one traversal:
        zero3_init already holds them)."""
        from apex_tpu.optimizers.distributed import ChunkedMeta

        def struct(p, ls, st):
            return jax.ShapeDtypeStruct(tuple(ls[1:]) if st else tuple(ls),
                                        p.dtype)

        return ChunkedMeta(
            shapes=jax.tree.map(struct, model_params, shapes, stacked),
            axis=self.zero_axis,
            gather_dtype=self.gather_dtype)

    def zero3_init(self, model_params, mesh, param_specs) -> Zero3Setup:
        """Initialize fully-sharded training state from host-side (global)
        params: places the working-param chunk tree, the fp32 master
        chunks + inner optimizer state (same per-row layout), and returns
        the :class:`Zero3Setup` bundle the train-step builder consumes
        (transformer.amp.build_zero_train_step). The chunk specs carry no
        replication assumption over ANY axis — stacked leaves shard their
        leading (layer) dim exactly as the param spec does (the pipeline
        axis), their chunk dim over everything else — so TP/pipe-sharded
        params round-trip correctly."""
        from apex_tpu.optimizers.distributed import chunk_size

        if self.zero_level < 3:
            raise ValueError("zero3_init requires zero_level=3")
        n = mesh.shape[self.zero_axis]
        shapes, treedef, spec_leaves = self._zero3_local_shapes(
            model_params, mesh, param_specs)
        stacked = self._stacked_tree(model_params)
        meta = self._zero3_meta_from(model_params, shapes, stacked)

        def prod(xs):
            size = 1
            for s in xs:
                size *= s
            return size

        def chunk_struct(p, ls, st, dtype):
            if st:
                return jax.ShapeDtypeStruct(
                    (ls[0], chunk_size(prod(ls[1:]), n)), dtype)
            return jax.ShapeDtypeStruct((chunk_size(prod(ls), n),), dtype)

        master_structs = jax.tree.map(
            lambda p, ls, st: chunk_struct(p, ls, st, jnp.float32),
            model_params, shapes, stacked)

        universal = P(tuple(mesh.axis_names))

        def chunk_spec(spec, st):
            if not st:
                return universal
            dim0 = spec[0] if spec is not None and len(spec) else None
            d0_axes = _spec_axis_names(dim0)
            rest = tuple(a for a in mesh.axis_names if a not in d0_axes)
            return P(dim0, rest)

        st_leaves = [bool(s) for s in jax.tree.leaves(stacked)]
        chunk_specs = treedef.unflatten(
            [chunk_spec(s, st) for s, st in zip(spec_leaves, st_leaves)])
        stacked_specs = {chunk_spec(s, True) for s, st
                         in zip(spec_leaves, st_leaves) if st}
        if len(stacked_specs) > 1:
            raise ValueError(
                f"stacked layer leaves carry inconsistent leading-dim "
                f"specs {sorted(map(str, stacked_specs))}: the sharded "
                f"optimizer-state specs need one uniform (L, chunk) "
                f"placement")
        stacked_spec = (stacked_specs.pop() if stacked_specs
                        else P(None, tuple(mesh.axis_names)))

        scaler = _scaler_from_policy(self.policy, **self._scaler_kwargs)
        abstract_state = jax.eval_shape(
            lambda m: MPOptState(inner=self.inner.init(m), master=m,
                                 scaler=scaler),
            master_structs)
        # chunks are 1-D (or (L, chunk) for stacked leaves) BY CONSTRUCTION,
        # so rank alone classifies state leaves: scalars (step counters, the
        # scaler) replicate, everything else is a per-device shard
        state_specs = jax.tree.map(
            lambda x: (stacked_spec if getattr(x, "ndim", 0) == 2
                       else universal if getattr(x, "ndim", 0) == 1
                       else P()),
            abstract_state)

        init = jax.jit(jax.shard_map(
            lambda p: (self.zero3_shard(p), self.init(p)),
            mesh=mesh, in_specs=(param_specs,),
            out_specs=(chunk_specs, state_specs), check_vma=False))
        chunks, state = init(model_params)
        return Zero3Setup(params=chunks, param_specs=chunk_specs,
                          opt_state=state, state_specs=state_specs,
                          meta=meta)

    def zero3_materialize(self, setup: Zero3Setup, mesh, param_specs,
                          param_chunks=None):
        """Gather the full (global) params back from a chunk tree — for
        checkpointed-weight export, eval harnesses, and the equivalence
        tests. Host-side helper (one jitted shard_map); the TRAIN path
        never calls this — materializing the whole model is exactly what
        ZeRO-3 removes. Wire dtype is each leaf's own (exact round-trip)."""
        from apex_tpu.optimizers.distributed import (
            gather_leaf,
            gather_stacked_leaf,
        )

        chunks = setup.params if param_chunks is None else param_chunks
        stacked = self._stacked_tree(chunks)
        meta = setup.meta

        def gather_all(c_tree):
            return jax.tree.map(
                lambda c, s, st: (
                    gather_stacked_leaf(c, s.shape, s.dtype, self.zero_axis)
                    if st else
                    gather_leaf(c, s.shape, s.dtype, self.zero_axis)),
                c_tree, meta.shapes, stacked)

        fn = jax.jit(jax.shard_map(
            gather_all, mesh=mesh, in_specs=(setup.param_specs,),
            out_specs=param_specs, check_vma=False))
        return fn(chunks)

    # -- checkpointing (apex/amp/frontend.py:361-400) -----------------------
    def state_dict(self, state: MPOptState):
        return {"scaler": state.scaler.state_dict()}

    def load_state_dict(self, state: MPOptState, payload) -> MPOptState:
        return state._replace(scaler=state.scaler.load_state_dict(payload["scaler"]))


class AmpTrainState(struct.PyTreeNode):
    """Bundled train state: params + amp optimizer state + step counter.

    The functional analog of "model, optimizer = amp.initialize(...)" followed
    by a torch train loop; built by :func:`initialize`.
    """

    step: jax.Array
    params: Any
    opt_state: MPOptState
    apply_fn: Callable = struct.field(pytree_node=False)
    mp_optimizer: MixedPrecisionOptimizer = struct.field(pytree_node=False)

    @classmethod
    def create(cls, *, apply_fn, params, mp_optimizer):
        return cls(
            step=jnp.zeros([], jnp.int32),
            params=params,
            opt_state=mp_optimizer.init(params),
            apply_fn=apply_fn,
            mp_optimizer=mp_optimizer,
        )

    @property
    def scaler(self) -> LossScaler:
        return self.opt_state.scaler

    def scale_loss(self, loss):
        return self.mp_optimizer.scale_loss(loss, self.opt_state)

    def apply_gradients(self, scaled_grads, *, found_inf_reducer=None, **kw):
        new_params, new_opt, metrics = self.mp_optimizer.apply_gradients(
            self.opt_state,
            self.params,
            scaled_grads,
            found_inf_reducer=found_inf_reducer,
            **kw,
        )
        return (
            self.replace(step=self.step + 1, params=new_params, opt_state=new_opt),
            metrics,
        )


def initialize(
    params,
    optimizers=None,
    opt_level: str = "O1",
    *,
    apply_fn: Optional[Callable] = None,
    cast_model_type=None,
    keep_batchnorm_fp32=None,
    master_weights=None,
    loss_scale=None,
    min_loss_scale: Optional[float] = None,
    max_loss_scale: float = 2.0 ** 24,
    half_dtype=jnp.bfloat16,
    verbosity: int = 1,
):
    """TPU-native ``amp.initialize`` (reference: apex/amp/frontend.py:195-358).

    Args mirror the reference's keyword surface where meaningful.
    ``optimizers`` may be a single optax transform / ClassOptimizer, or None
    for inference-only use (the reference's optimizers=None path,
    _initialize.py:220-222).

    Returns:
      - with an optimizer and ``apply_fn``: an :class:`AmpTrainState`;
      - with an optimizer, no ``apply_fn``: ``(cast_params, mp_optimizer)``;
      - with ``optimizers=None``: ``(cast_params, policy)``.
    """
    policy = _precision.get_policy(
        opt_level,
        half_dtype=half_dtype,
        cast_model_type=cast_model_type,
        keep_batchnorm_fp32=keep_batchnorm_fp32,
        master_weights=master_weights,
        loss_scale=loss_scale,
    )
    if verbosity:
        from apex_tpu.utils.log_util import maybe_print

        maybe_print(
            f"apex_tpu.amp: opt_level={policy.opt_level} cast_model_type="
            f"{policy.cast_model_type} master_weights={policy.master_weights} "
            f"loss_scale={policy.loss_scale}",
            rank0=True,
        )

    # arm the O1-style function registries (amp.py:68-177's patch install)
    from apex_tpu.amp.functions import set_active_policy

    set_active_policy(policy)
    cast = _precision.cast_params(params, policy)
    if optimizers is None:
        if apply_fn is not None:
            raise ValueError(
                "apply_fn without an optimizer has nothing to train; call "
                "initialize(params, opt_level=...) for inference casting, or "
                "pass an optimizer to build an AmpTrainState."
            )
        return cast, policy

    mp_opt = MixedPrecisionOptimizer(
        optimizers,
        policy,
        min_loss_scale=min_loss_scale,
        max_loss_scale=max_loss_scale,
    )
    if apply_fn is not None:
        return AmpTrainState.create(apply_fn=apply_fn, params=cast, mp_optimizer=mp_opt)
    return cast, mp_opt
