"""amp frontend — initialize, mixed-precision optimizer, train state.

The TPU-native re-design of apex.amp's user surface:

- ``initialize(params, optimizer, opt_level=..., **overrides)`` mirrors
  ``apex.amp.initialize`` (reference: apex/amp/frontend.py:195-358 +
  _initialize.py:145-263): casts params per policy, wraps the optimizer with
  master weights + loss scaling + overflow skip.
- ``MixedPrecisionOptimizer`` replaces the reference's in-place optimizer
  surgery (_process_optimizer.py:321-489: ``_amp_stash`` master clones, patched
  ``step``/``zero_grad``, pre/post-backward hooks). In functional JAX all of
  that state is an explicit pytree and "patching step" is a ``lax.cond``.
- ``AmpTrainState`` is the convenience bundle (flax TrainState analog) used by
  the examples.

What has no analog and why: O1's namespace monkey-patching
(apex/amp/amp.py:68-177) casts call sites at runtime; under tracing, casts are
explicit in the model code, so O1 here means "params fp32, compute bf16" via
policy-aware modules (see apex_tpu.precision.Policy.op_dtype).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import optax
from flax import struct

from apex_tpu import precision as _precision
from apex_tpu.amp.scaler import LossScaler
from apex_tpu.ops.multi_tensor import tree_l2norm, tree_scale
from apex_tpu.optimizers._common import ClassOptimizer


class MPOptState(NamedTuple):
    """Optimizer + amp carried state.

    ``master`` holds fp32 master weights when the policy asks for them
    (the ``_amp_stash`` fp32_from_fp16 groups of _process_optimizer.py:28-90);
    otherwise None. ``inner`` is the wrapped transform's state, always built
    over the fp32 view of params. ``scaler`` is the loss-scale state machine.
    """

    inner: Any
    master: Any
    scaler: LossScaler


def _scaler_from_policy(policy: _precision.Policy, **scaler_kwargs) -> LossScaler:
    return LossScaler.create(loss_scale=policy.loss_scale, **scaler_kwargs)


class MixedPrecisionOptimizer:
    """Wraps an optax transform with amp semantics.

    Per step (cf. the reference's scale_loss exit path, handle.py:107-154, and
    patched step, _process_optimizer.py:353-364):

    1. unscale grads by 1/loss_scale into fp32, detecting non-finites;
    2. all-reduce of found_inf is the caller's job when running under a mesh
       (see apex_tpu.transformer.amp.MeshGradScaler);
    3. ``lax.cond(found_inf)``: skip (state unchanged) or apply the inner
       update to the fp32 master params;
    4. cast masters back to the model dtypes (multi_tensor_scale copy-out,
       _process_optimizer.py:14-25);
    5. scaler.update(found_inf).
    """

    def __init__(
        self,
        optimizer: Union[optax.GradientTransformation, ClassOptimizer],
        policy: _precision.Policy,
        log_grad_norm: bool = False,
        log_group_norms: bool = False,
        **scaler_kwargs,
    ):
        self.inner = (
            optimizer.transform if isinstance(optimizer, ClassOptimizer) else optimizer
        )
        self.policy = policy
        #: when True, ``apply_gradients`` metrics include the global L2 norm
        #: of the unscaled grads — the journal hook (monitor/journal.py).
        #: Off by default: the extra tree reduction, while small next to the
        #: step's matmuls, must be opt-in so uninstrumented programs stay
        #: byte-identical.
        self.log_grad_norm = bool(log_grad_norm)
        #: when True, metrics also carry ``grad_norm_by_group`` — the L2
        #: norm per top-level parameter group (monitor/diagnose.py's
        #: overflow-forensics breakdown: a group whose norm is non-finite
        #: names the first non-finite layer from the journal alone). Same
        #: opt-in byte-identity contract as ``log_grad_norm``.
        self.log_group_norms = bool(log_group_norms)
        self._scaler_kwargs = scaler_kwargs

    def init(self, model_params) -> MPOptState:
        if self.policy.master_weights:
            master = _precision.upcast_params(model_params)
        else:
            master = None
        inner = self.inner.init(master if master is not None else model_params)
        return MPOptState(
            inner=inner,
            master=master,
            scaler=_scaler_from_policy(self.policy, **self._scaler_kwargs),
        )

    def scale_loss(self, loss: jax.Array, state: MPOptState) -> jax.Array:
        """``with amp.scale_loss(...)`` enter path (handle.py:113)."""
        return state.scaler.scale(loss)

    def apply_gradients(
        self,
        state: MPOptState,
        model_params,
        scaled_grads,
        *,
        found_inf_reducer: Optional[Callable[[jax.Array], jax.Array]] = None,
        **update_kwargs,
    ):
        """Returns ``(new_model_params, new_state, metrics)``.

        ``scaled_grads`` are grads of the *scaled* loss w.r.t. model params.
        ``found_inf_reducer`` lets callers all-reduce the overflow flag across
        a mesh axis (the model-parallel reduction of
        apex/transformer/amp/grad_scaler.py:25-36).
        """
        grads32, found_inf = state.scaler.unscale(scaled_grads, out_dtype=jnp.float32)
        if found_inf_reducer is not None:
            found_inf = found_inf_reducer(found_inf)

        step_params = state.master if state.master is not None else model_params

        def _do_step(operand):
            params, inner_state = operand
            updates, new_inner = self.inner.update(
                grads32, inner_state, params, **update_kwargs
            )
            new_params = optax.apply_updates(params, updates)
            return new_params, new_inner

        def _skip_step(operand):
            return operand

        new_step_params, new_inner = jax.lax.cond(
            found_inf, _skip_step, _do_step, (step_params, state.inner)
        )

        if state.master is not None:
            # master -> model copy-out in the model dtypes.
            new_model = jax.tree.map(
                lambda mp, p: mp.astype(p.dtype), new_step_params, model_params
            )
            new_master = new_step_params
        else:
            new_model = new_step_params
            new_master = None

        new_scaler = state.scaler.update(found_inf)
        metrics = {
            "found_inf": found_inf,
            "loss_scale": new_scaler.loss_scale,
        }
        if self.log_grad_norm:
            # fp16_utils.FP16_Optimizer.step reports this unconditionally;
            # here it rides the metrics dict only when asked for
            metrics["grad_norm"] = tree_l2norm(grads32)
        if self.log_group_norms:
            from apex_tpu.monitor.diagnose import group_grad_norms

            metrics["grad_norm_by_group"] = group_grad_norms(grads32)
        return new_model, MPOptState(new_inner, new_master, new_scaler), metrics

    # -- checkpointing (apex/amp/frontend.py:361-400) -----------------------
    def state_dict(self, state: MPOptState):
        return {"scaler": state.scaler.state_dict()}

    def load_state_dict(self, state: MPOptState, payload) -> MPOptState:
        return state._replace(scaler=state.scaler.load_state_dict(payload["scaler"]))


class AmpTrainState(struct.PyTreeNode):
    """Bundled train state: params + amp optimizer state + step counter.

    The functional analog of "model, optimizer = amp.initialize(...)" followed
    by a torch train loop; built by :func:`initialize`.
    """

    step: jax.Array
    params: Any
    opt_state: MPOptState
    apply_fn: Callable = struct.field(pytree_node=False)
    mp_optimizer: MixedPrecisionOptimizer = struct.field(pytree_node=False)

    @classmethod
    def create(cls, *, apply_fn, params, mp_optimizer):
        return cls(
            step=jnp.zeros([], jnp.int32),
            params=params,
            opt_state=mp_optimizer.init(params),
            apply_fn=apply_fn,
            mp_optimizer=mp_optimizer,
        )

    @property
    def scaler(self) -> LossScaler:
        return self.opt_state.scaler

    def scale_loss(self, loss):
        return self.mp_optimizer.scale_loss(loss, self.opt_state)

    def apply_gradients(self, scaled_grads, *, found_inf_reducer=None, **kw):
        new_params, new_opt, metrics = self.mp_optimizer.apply_gradients(
            self.opt_state,
            self.params,
            scaled_grads,
            found_inf_reducer=found_inf_reducer,
            **kw,
        )
        return (
            self.replace(step=self.step + 1, params=new_params, opt_state=new_opt),
            metrics,
        )


def initialize(
    params,
    optimizers=None,
    opt_level: str = "O1",
    *,
    apply_fn: Optional[Callable] = None,
    cast_model_type=None,
    keep_batchnorm_fp32=None,
    master_weights=None,
    loss_scale=None,
    min_loss_scale: Optional[float] = None,
    max_loss_scale: float = 2.0 ** 24,
    half_dtype=jnp.bfloat16,
    verbosity: int = 1,
):
    """TPU-native ``amp.initialize`` (reference: apex/amp/frontend.py:195-358).

    Args mirror the reference's keyword surface where meaningful.
    ``optimizers`` may be a single optax transform / ClassOptimizer, or None
    for inference-only use (the reference's optimizers=None path,
    _initialize.py:220-222).

    Returns:
      - with an optimizer and ``apply_fn``: an :class:`AmpTrainState`;
      - with an optimizer, no ``apply_fn``: ``(cast_params, mp_optimizer)``;
      - with ``optimizers=None``: ``(cast_params, policy)``.
    """
    policy = _precision.get_policy(
        opt_level,
        half_dtype=half_dtype,
        cast_model_type=cast_model_type,
        keep_batchnorm_fp32=keep_batchnorm_fp32,
        master_weights=master_weights,
        loss_scale=loss_scale,
    )
    if verbosity:
        from apex_tpu.utils.log_util import maybe_print

        maybe_print(
            f"apex_tpu.amp: opt_level={policy.opt_level} cast_model_type="
            f"{policy.cast_model_type} master_weights={policy.master_weights} "
            f"loss_scale={policy.loss_scale}",
            rank0=True,
        )

    # arm the O1-style function registries (amp.py:68-177's patch install)
    from apex_tpu.amp.functions import set_active_policy

    set_active_policy(policy)
    cast = _precision.cast_params(params, policy)
    if optimizers is None:
        if apply_fn is not None:
            raise ValueError(
                "apply_fn without an optimizer has nothing to train; call "
                "initialize(params, opt_level=...) for inference casting, or "
                "pass an optimizer to build an AmpTrainState."
            )
        return cast, policy

    mp_opt = MixedPrecisionOptimizer(
        optimizers,
        policy,
        min_loss_scale=min_loss_scale,
        max_loss_scale=max_loss_scale,
    )
    if apply_fn is not None:
        return AmpTrainState.create(apply_fn=apply_fn, params=cast, mp_optimizer=mp_opt)
    return cast, mp_opt
