"""Loss scaling as a functional pytree state machine.

Reference: apex/amp/scaler.py — ``LossScaler`` with static or dynamic scale
(init 2**16, x2 every 2000 clean steps, /2 on overflow, min/max caps,
scaler.py:38-71,197-217) and fused unscale-with-overflow-check
(scaler.py:105-178). In JAX the scaler must be explicit carried state (the
reference mutates ``self``); skip-on-overflow becomes a ``lax.cond`` in the
optimizer rather than patching ``optimizer.step`` (apex/amp/handle.py:127-154).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import struct

from apex_tpu.ops.multi_tensor import tree_nonfinite, tree_scale


@struct.dataclass
class LossScaler:
    """Carried loss-scale state. Create with ``LossScaler.create``.

    Fields mirror apex/amp/scaler.py:38-61: ``loss_scale`` (current scale),
    ``unskipped`` (clean-step counter), and static config ``dynamic``,
    ``scale_factor``, ``scale_window``, ``min_loss_scale``, ``max_loss_scale``.
    """

    loss_scale: jax.Array
    unskipped: jax.Array
    dynamic: bool = struct.field(pytree_node=False, default=False)
    scale_factor: float = struct.field(pytree_node=False, default=2.0)
    scale_window: int = struct.field(pytree_node=False, default=2000)
    # None = no floor, matching the reference default (apex/amp/scaler.py:43)
    min_loss_scale: Optional[float] = struct.field(pytree_node=False, default=None)
    max_loss_scale: float = struct.field(pytree_node=False, default=2.0 ** 24)

    @classmethod
    def create(
        cls,
        loss_scale: Union[str, float] = "dynamic",
        init_scale: float = 2.0 ** 16,
        scale_factor: float = 2.0,
        scale_window: int = 2000,
        min_loss_scale: Optional[float] = None,
        max_loss_scale: float = 2.0 ** 24,
    ) -> "LossScaler":
        dynamic = loss_scale == "dynamic"
        scale = init_scale if dynamic else float(loss_scale)
        return cls(
            loss_scale=jnp.asarray(scale, jnp.float32),
            unskipped=jnp.asarray(0, jnp.int32),
            dynamic=dynamic,
            scale_factor=scale_factor,
            scale_window=scale_window,
            min_loss_scale=min_loss_scale,
            max_loss_scale=max_loss_scale,
        )

    # -- forward side -------------------------------------------------------
    def scale(self, loss: jax.Array) -> jax.Array:
        """``loss.float() * loss_scale`` (apex/amp/handle.py:113)."""
        return loss.astype(jnp.float32) * self.loss_scale

    # -- backward side ------------------------------------------------------
    def unscale(self, grads, out_dtype=None) -> Tuple[Any, jax.Array]:
        """Unscale a grad tree, returning ``(grads, found_inf)``.

        Equivalent of ``LossScaler.unscale`` driving
        ``multi_tensor_scale(1/scale)`` with the overflow buffer
        (apex/amp/scaler.py:105-117).
        """
        inv = 1.0 / self.loss_scale
        return tree_scale(grads, inv, out_dtype=out_dtype)

    def update(self, found_inf: jax.Array) -> "LossScaler":
        """Post-step scale adjustment (apex/amp/scaler.py:197-217).

        On overflow: scale /= factor (floored at min), counter reset. After
        ``scale_window`` clean steps: scale *= factor (capped at max).
        """
        if not self.dynamic:
            return self
        found_inf = jnp.asarray(found_inf)
        new_unskipped = jnp.where(found_inf, 0, self.unskipped + 1)
        grown = new_unskipped >= self.scale_window
        floor = self.min_loss_scale if self.min_loss_scale is not None else 0.0
        scale = jnp.where(
            found_inf,
            jnp.maximum(self.loss_scale / self.scale_factor, floor),
            jnp.where(
                grown,
                jnp.minimum(self.loss_scale * self.scale_factor, self.max_loss_scale),
                self.loss_scale,
            ),
        )
        new_unskipped = jnp.where(grown, 0, new_unskipped)
        return self.replace(loss_scale=scale, unskipped=new_unskipped)

    # -- checkpointing (apex/amp/frontend.py:361-400) -----------------------
    def state_dict(self):
        return {
            "loss_scale": self.loss_scale,
            "unskipped": self.unskipped,
        }

    def load_state_dict(self, state) -> "LossScaler":
        return self.replace(
            loss_scale=jnp.asarray(state["loss_scale"], jnp.float32),
            unskipped=jnp.asarray(state["unskipped"], jnp.int32),
        )


def check_overflow(grads) -> jax.Array:
    """Standalone overflow probe (apex/amp/scaler.py:6-31 python fallback)."""
    return tree_nonfinite(grads)
