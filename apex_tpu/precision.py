"""Precision policies — the TPU-native equivalent of apex.amp opt levels.

The reference encodes mixed precision as an ``apex.amp.Properties`` object with
four preset "opt levels" O0–O3 plus keyword overrides
(reference: apex/amp/frontend.py:7-191). On CUDA the O1 level is implemented by
monkey-patching torch namespaces with cast wrappers; that mechanism has no JAX
analog (and needs none: tracing makes casts explicit), so here a policy is a
frozen dataclass consumed by

- ``apex_tpu.amp.initialize`` / ``MixedPrecisionOptimizer`` (master weights,
  loss scaling, param casting), and
- policy-aware modules (``apex_tpu.nn_util.Dense`` etc.) which consult
  ``compute_dtype`` / ``fp32_ops`` instead of relying on patched call sites.

Semantics preserved from the reference presets (apex/amp/frontend.py:100-191):

====== ==================== ================= ============== ===========
level  cast_model_type      compute_dtype     master_weights loss_scale
====== ==================== ================= ============== ===========
O0     None (fp32)          fp32              False          1.0
O1     None (fp32 params)   bf16 (whitelist)  False          "dynamic"
O2     bf16 (norms fp32)    bf16              True           "dynamic"
O3     bf16                 bf16              False          1.0
====== ==================== ================= ============== ===========

On TPU the natural half dtype is bfloat16 (no loss scaling strictly required,
but retained for parity and for fp16 experiments — pass
``half_dtype=jnp.float16``).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, FrozenSet, Optional, Union

import jax
import jax.numpy as jnp

# Op families that stay fp32 under an O1-style policy. Mirrors the FP32
# blacklist of the reference (apex/amp/lists/torch_overrides.py:29-61,
# functional_overrides.py:29-68): softmax-like, exponential/log, norms, losses.
_DEFAULT_FP32_OPS: FrozenSet[str] = frozenset(
    {
        "softmax",
        "log_softmax",
        "layer_norm",
        "rms_norm",
        "batch_norm",
        "group_norm",
        "cross_entropy",
        "mse_loss",
        "l1_loss",
        "exp",
        "log",
        "pow",
        "sum",
        "mean",
        "norm",
        "cumsum",
        "erf",
        "softplus",
        "sigmoid_loss",
    }
)

# Normalization families — stay fp32 under keep_batchnorm_fp32 even when the
# model is cast (O2), like apex re-floating _BatchNorm (fp16util.py:42-49).
_NORM_OPS: FrozenSet[str] = frozenset(
    {"batch_norm", "layer_norm", "rms_norm", "group_norm"}
)

# Op families computed in the half dtype under O1 — the FP16 whitelist
# (apex/amp/lists/torch_overrides.py:7-27): matmuls and convolutions, i.e.
# everything that lands on the MXU.
_DEFAULT_HALF_OPS: FrozenSet[str] = frozenset(
    {"matmul", "conv", "dense", "attention", "einsum", "mlp"}
)


def _canon(dt: Optional[Any]) -> Optional[jnp.dtype]:
    if dt is None:
        return None
    return jnp.dtype(dt)


@dataclasses.dataclass(frozen=True)
class Policy:
    """A mixed-precision policy (apex ``Properties`` equivalent).

    Attributes:
      opt_level: "O0" | "O1" | "O2" | "O3" (informational once constructed).
      cast_model_type: dtype model params are stored in, or None for fp32.
      compute_dtype: dtype MXU-bound ops compute in.
      keep_batchnorm_fp32: keep norm/batchnorm params + stats fp32 even when
        params are cast (reference: frontend.py:150-162 O2 default True).
      master_weights: keep an fp32 master copy of params inside the optimizer
        (reference: _process_optimizer.py:28-90).
      loss_scale: "dynamic" or a static float (reference: frontend.py:163-168).
      fp32_ops: op-family names forced to fp32 (O1 blacklist equivalent).
      half_ops: op-family names allowed in compute_dtype (O1 whitelist).
    """

    opt_level: str = "O0"
    cast_model_type: Optional[jnp.dtype] = None
    compute_dtype: jnp.dtype = dataclasses.field(default_factory=lambda: jnp.dtype(jnp.float32))
    keep_batchnorm_fp32: bool = True
    master_weights: bool = False
    loss_scale: Union[str, float] = 1.0
    fp32_ops: FrozenSet[str] = _DEFAULT_FP32_OPS
    half_ops: FrozenSet[str] = _DEFAULT_HALF_OPS

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == "dynamic"

    @property
    def param_dtype(self) -> jnp.dtype:
        return self.cast_model_type or jnp.dtype(jnp.float32)

    def op_dtype(self, op_family: str) -> jnp.dtype:
        """Compute dtype for an op family under this policy.

        With uncast (fp32) params — O0/O1 — the op lists govern:
        blacklisted families are fp32, whitelisted families follow
        ``compute_dtype``, and families on neither list stay fp32 (the
        conservative reading of the reference's promote/passthrough lists:
        under O1 inputs derive from fp32 params, so type promotion resolves
        to fp32; apex/amp/lists/torch_overrides.py:63-115).

        With a cast model — O2/O3 — the whole network runs in
        ``compute_dtype`` (the reference casts the model wholesale,
        _initialize.py:176-182) except normalization families when
        ``keep_batchnorm_fp32`` asks for fp32 norms (frontend.py:150-162)."""
        if self.cast_model_type is None:
            if op_family in self.fp32_ops:
                return jnp.dtype(jnp.float32)
            if op_family in self.half_ops:
                return self.compute_dtype
            return jnp.dtype(jnp.float32)
        if self.keep_batchnorm_fp32 and op_family in _NORM_OPS:
            return jnp.dtype(jnp.float32)
        return self.compute_dtype

    def cast_to_compute(self, x, op_family: str = "matmul"):
        """Cast an array (or pytree) to this policy's compute dtype for an op."""
        dt = self.op_dtype(op_family)
        return jax.tree.map(
            lambda a: a.astype(dt) if jnp.issubdtype(a.dtype, jnp.floating) else a, x
        )


def _make_policy(
    opt_level: str,
    half_dtype=jnp.bfloat16,
    **overrides,
) -> Policy:
    half = jnp.dtype(half_dtype)
    presets = {
        "O0": dict(
            cast_model_type=None,
            compute_dtype=jnp.dtype(jnp.float32),
            keep_batchnorm_fp32=True,
            master_weights=False,
            loss_scale=1.0,
        ),
        "O1": dict(
            cast_model_type=None,
            compute_dtype=half,
            keep_batchnorm_fp32=True,
            master_weights=False,
            loss_scale="dynamic",
        ),
        "O2": dict(
            cast_model_type=half,
            compute_dtype=half,
            keep_batchnorm_fp32=True,
            master_weights=True,
            loss_scale="dynamic",
        ),
        "O3": dict(
            cast_model_type=half,
            compute_dtype=half,
            keep_batchnorm_fp32=False,
            master_weights=False,
            loss_scale=1.0,
        ),
    }
    if opt_level not in presets:
        raise ValueError(
            f"Unexpected optimization level {opt_level!r}; options are 'O0', 'O1', 'O2', 'O3'."
        )
    cfg = presets[opt_level]
    for k, v in overrides.items():
        if v is None:
            continue
        if k not in cfg and k not in {"fp32_ops", "half_ops"}:
            raise ValueError(f"Unknown policy override {k!r}")
        cfg[k] = v
    if cfg.get("cast_model_type") is not None and (
        "fp32_ops" in overrides and overrides["fp32_ops"] is not None
        or "half_ops" in overrides and overrides["half_ops"] is not None
    ):
        raise ValueError(
            "fp32_ops/half_ops only govern uncast-model policies (O0/O1); a "
            "cast model (O2/O3) runs wholesale in compute_dtype — use "
            "keep_batchnorm_fp32 for fp32 norms."
        )
    if "cast_model_type" in cfg:
        cfg["cast_model_type"] = _canon(cfg["cast_model_type"])
    if "compute_dtype" in cfg:
        cfg["compute_dtype"] = _canon(cfg["compute_dtype"])
    return Policy(opt_level=opt_level, **cfg)


def get_policy(opt_level: Union[str, Policy] = "O1", **overrides) -> Policy:
    """Build a Policy from an opt level + overrides (apex frontend.py:195-358)."""
    if isinstance(opt_level, Policy):
        live = {k: v for k, v in overrides.items() if v is not None and k != "half_dtype"}
        if live:
            raise ValueError(
                f"Overrides {sorted(live)} cannot be combined with a pre-built "
                "Policy; pass an opt-level string, or dataclasses.replace the Policy."
            )
        return opt_level
    return _make_policy(opt_level, **overrides)


# ---------------------------------------------------------------------------
# Param-tree casting helpers (replace convert_network, fp16util.py:35-99)
# ---------------------------------------------------------------------------

# Module-path patterns that mark normalization layers (kept fp32 under
# keep_batchnorm_fp32, like apex's _BatchNorm re-float, fp16util.py:42-49):
# any name containing "norm" (batchnorm, layernorm, BatchNorm_0, norm1, ...)
# or a standalone bn/ln token ("bn", "bn1", "bn_2", "ln1", "ln_f",
# "downsample_bn").
_BN_TOKEN_RE = re.compile(r"(^|[._/])(bn|ln)\d*([._/]|$)")


def _name_is_norm(name: str) -> bool:
    n = name.lower()
    return "norm" in n or _BN_TOKEN_RE.search(n) is not None


def _path_is_norm(path) -> bool:
    for p in path:
        if hasattr(p, "key"):
            name = str(p.key)
        elif hasattr(p, "name"):
            name = str(p.name)
        else:
            continue
        if _name_is_norm(name):
            return True
    return False


def cast_floats(params, dtype, keep_norms_fp32: bool = True):
    """Cast floating leaves to ``dtype``, keeping norm-path leaves fp32 when
    asked — the shared engine behind ``cast_params`` and the legacy
    ``fp16_utils.convert_network`` (fp16util.py:44-58)."""

    def _cast(path, leaf):
        if not _is_float_array(leaf):
            return leaf
        if keep_norms_fp32 and _path_is_norm(path):
            return jnp.asarray(leaf, jnp.float32)
        return jnp.asarray(leaf, dtype)

    return jax.tree_util.tree_map_with_path(_cast, params)


def cast_params(params, policy: Policy):
    """Cast a param pytree per policy (reference: _initialize.py:176-182).

    Floating-point leaves are cast to ``policy.param_dtype``; when
    ``keep_batchnorm_fp32`` is set, leaves living under a module whose path
    contains a norm marker stay fp32 (the analog of apex converting
    ``torch.nn.modules.batchnorm._BatchNorm`` back to float,
    fp16util.py:42-49).
    """
    if policy.cast_model_type is None:
        return params
    return cast_floats(
        params, policy.cast_model_type,
        keep_norms_fp32=policy.keep_batchnorm_fp32,
    )


def _is_float_array(a) -> bool:
    """True for jax *and* numpy array leaves with a floating dtype (numpy
    params arrive from checkpoint loaders and must be cast too)."""
    return hasattr(a, "dtype") and hasattr(a, "shape") and jnp.issubdtype(a.dtype, jnp.floating)


def upcast_params(params, dtype=jnp.float32):
    """Cast all floating leaves up (master-weight init; fp16util.py:100-126)."""
    return jax.tree.map(
        lambda a: jnp.asarray(a, dtype) if _is_float_array(a) else a, params
    )
