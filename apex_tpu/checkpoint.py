"""Checkpoint / resume (reference: SURVEY.md §5 "Checkpoint / resume").

The reference's checkpoint story is three state dicts — model, optimizer, and
``amp.state_dict()`` for the loss scaler (apex/amp/frontend.py:361-400,
README.md:59-99) — plus ``FP16_Optimizer.state_dict`` for the legacy path
(fp16_utils/fp16_optimizer.py:209-271). Here the whole train state (params,
``MPOptState`` incl. fp32 masters and scaler, anything else) is one pytree,
so a checkpoint is one atomic save of that tree.

Design points (TPU-native):

- **orbax** backend when available (async-capable, multi-host aware), with a
  dependency-free ``.npz`` fallback so the module works anywhere;
- **topology-independent**: the tree is saved in its logical (unsharded)
  shapes — orbax writes sharded ``jax.Array`` leaves shard-by-shard without
  a host gather (multi-host safe); npz host-gathers. On restore the caller
  passes whatever ``NamedSharding`` the *new* mesh prescribes
  (``restore(..., sharding_tree=)``) and leaves materialize directly into
  it, or omits it to get host numpy on any topology — resume may change
  mesh shape (SURVEY.md §5 failure-detection note);
- step-numbered directories with ``latest_step`` discovery, the
  ``save_checkpoint``/``load_checkpoint`` UX of Megatron-style trainers.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Optional

import jax
import numpy as np

try:  # pragma: no cover - exercised via the public API either way
    import orbax.checkpoint as _ocp
except Exception:  # noqa: BLE001 - any import failure selects the fallback
    _ocp = None

_STEP_RE = re.compile(r"^step_(\d+)$")
_SEP = "/"


def _path_key(path) -> str:
    parts = []
    for p in path:
        for attr in ("key", "name", "idx"):
            if hasattr(p, attr):
                parts.append(str(getattr(p, attr)))
                break
        else:
            parts.append(str(p))
    return _SEP.join(parts)


_META_KEY = "__apex_tpu_dtypes__"


def _flatten(tree) -> dict:
    """Flatten to {path: ndarray}. Non-native dtypes (bfloat16, fp8 — numpy
    would silently store them as raw void and break round-trips) are saved as
    byte arrays with (dtype, shape) recorded under ``_META_KEY``."""
    flat = {}
    meta = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = _path_key(path)
        arr = np.asarray(jax.device_get(leaf))
        if arr.dtype.kind == "V" or not arr.dtype.isbuiltin:
            meta[key] = {"dtype": arr.dtype.name, "shape": list(arr.shape)}
            arr = np.ascontiguousarray(arr).reshape(-1).view(np.uint8)
        flat[key] = arr
    flat[_META_KEY] = np.frombuffer(
        json.dumps(meta).encode("utf-8"), dtype=np.uint8
    )
    return flat


def _unflatten_into(target, flat: dict):
    """Rebuild ``target``'s structure from the flat mapping (missing keys are
    an error; dtype/shape come from the saved arrays)."""
    meta = {}
    if _META_KEY in flat:
        meta = json.loads(bytes(np.asarray(flat[_META_KEY])).decode("utf-8"))
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(target)
    new_leaves = []
    for path, leaf in leaves_with_path:
        key = _path_key(path)
        if key not in flat:
            raise KeyError(f"checkpoint missing leaf {key!r}")
        arr = flat[key]
        if key in meta:
            import jax.numpy as jnp

            dt = jnp.dtype(meta[key]["dtype"])
            arr = np.asarray(arr).view(dt).reshape(meta[key]["shape"])
        new_leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step}")


def latest_step(directory: str) -> Optional[int]:
    """Largest saved step number, or None (the auto-resume discovery the
    reference leaves as an unused slot, pipeline_parallel/utils.py:35)."""
    if not os.path.isdir(directory):
        return None
    steps = [
        int(m.group(1))
        for name in os.listdir(directory)
        if (m := _STEP_RE.match(name))
    ]
    return max(steps) if steps else None


def save_checkpoint(directory: str, step: int, state: Any, *, backend: str = "auto") -> str:
    """Save ``state`` (any pytree: params, MPOptState, FP16OptState, …) under
    ``directory/step_{step}``. Returns the checkpoint path.

    With the orbax backend, sharded ``jax.Array`` leaves are saved **without
    a host gather** — every host/process writes only its own shards (orbax's
    multi-host OCDBT protocol), so the same call scales from one chip to a
    multi-host pod. The npz fallback is a host-gathered single file and is
    only suitable single-host."""
    use_orbax = _ocp is not None if backend == "auto" else backend == "orbax"
    if use_orbax and _ocp is None:
        raise RuntimeError("backend='orbax' requested but orbax is unavailable")
    path = _step_dir(directory, step)
    os.makedirs(directory, exist_ok=True)
    if use_orbax:
        ckptr = _ocp.PyTreeCheckpointer()
        ckptr.save(os.path.abspath(path), state, force=True)
    else:
        # guard BEFORE _flatten: its jax.device_get would raise an opaque
        # span-non-addressable-devices error on multi-host sharded leaves
        for keypath, leaf in jax.tree_util.tree_leaves_with_path(state):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                raise ValueError(
                    f"npz backend is single-host only, but leaf "
                    f"{jax.tree_util.keystr(keypath)!r} is not fully "
                    "addressable from this process (multi-host sharded "
                    "array). Use backend='orbax', which writes per-process "
                    "shards without a host gather"
                    + ("" if _ocp is not None
                       else " (orbax failed to import in this environment; "
                            "install it or gather the state to one host)")
                    + "."
                )
        os.makedirs(path, exist_ok=True)
        np.savez(os.path.join(path, "state.npz"), **_flatten(state))
    return path


def restore_checkpoint(
    directory: str,
    target: Any,
    step: Optional[int] = None,
    *,
    sharding_tree: Any = None,
    backend: str = "auto",
) -> Any:
    """Restore the pytree saved at ``step`` (default: latest) into the
    structure of ``target``.

    ``sharding_tree``: optional pytree of ``jax.sharding.Sharding`` (same
    structure, e.g. built from ``model.specs()`` and the *current* mesh).
    The current mesh need not match the one the checkpoint was saved on —
    resume may reshape (e.g. pp=2×tp=2 → tp=4); this is what makes resume
    topology-independent (SURVEY.md §5).

    With the orbax backend, shardings are honored **at read time**: each
    leaf materializes directly into its target ``NamedSharding``, every
    host/process reading only the byte ranges its shards need — no
    host-gathered full copy exists at any point, so restore scales to
    states larger than one host's memory. The npz path restores to host
    then ``device_put``s each leaf."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _step_dir(directory, step)
    npz = os.path.join(path, "state.npz")
    if backend == "npz" or (backend == "auto" and os.path.exists(npz)):
        with np.load(npz) as z:
            restored = _unflatten_into(target, dict(z))
        if sharding_tree is not None:
            restored = jax.tree.map(jax.device_put, restored, sharding_tree)
        return restored
    if _ocp is None:
        raise RuntimeError("orbax unavailable and no npz checkpoint found")
    ckptr = _ocp.PyTreeCheckpointer()
    if sharding_tree is not None:
        sds_target = jax.tree.map(
            lambda t, s: jax.ShapeDtypeStruct(
                np.shape(t), np.asarray(t).dtype if not hasattr(t, "dtype") else t.dtype,
                sharding=s,
            ),
            target,
            sharding_tree,
        )
        restore_args = _ocp.checkpoint_utils.construct_restore_args(sds_target)
        return ckptr.restore(
            os.path.abspath(path), item=sds_target, restore_args=restore_args
        )
    # No sharding_tree: restore every leaf as host numpy so the checkpoint
    # opens on any topology (inspection hosts, smaller pods) regardless of
    # the shardings it was saved with.
    restore_args = jax.tree.map(
        lambda _: _ocp.RestoreArgs(restore_type=np.ndarray), target
    )
    return ckptr.restore(
        os.path.abspath(path), item=target, restore_args=restore_args
    )
