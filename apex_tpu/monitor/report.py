"""Journal analysis + regression CLI: ``python -m apex_tpu.monitor.report``.

The judgment layer over ``MetricsJournal`` files — an operator (or the
driver) asks one question per mode:

- ``report <run.jsonl>``: is this run healthy? Prints throughput
  percentiles, stall gaps (wall-clock holes between step records — the
  wedged-tunnel / co-tenant-spike signature), the loss-spike list,
  HBM-growth trend (the below-Python leak detector's journal-side view),
  per-rank straggler skew, comm-bytes-per-axis rollup, MFU summary, and
  recompile/forensics rollups.
- ``compare <A.jsonl> <B.jsonl> [--threshold 0.05]``: did B regress
  against A? Exits non-zero on regression so the bench trajectory gets a
  machine gate instead of a human eyeballing two JSON lines.

Pure stdlib + host-side: no jax import, runs anywhere (including the
off-TPU CI that produced the journal on a virtual mesh). Input is
whatever ``MetricsJournal`` wrote — bench windows, ``pretrain_gpt.py
--journal`` steps, scaling-harness rows — including crash-truncated
files (``MetricsJournal.read`` tolerates a torn final line).

No reference-file citation: NVIDIA Apex has no journal/analysis layer;
this is the evidence-discipline extension (PERF_NOTES instrumentation
note) the ISSUE's diagnostics engine closes.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Any, Dict, List, Optional, Sequence

# stdlib-only sibling: the shared spike predicate / median keep the
# offline rollups here in lockstep with the online forensics triggers
from apex_tpu.monitor.diagnose import is_loss_spike, median as _median


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def _dist(vals: List[float]) -> Dict[str, float]:
    s = sorted(v for v in vals if v is not None)
    if not s:
        return {}
    return {"p10": round(_percentile(s, 0.10), 3),
            "p50": round(_percentile(s, 0.50), 3),
            "p90": round(_percentile(s, 0.90), 3),
            "min": round(s[0], 3), "max": round(s[-1], 3), "n": len(s)}


def _dist_tail(vals: List[float]) -> Dict[str, float]:
    """:func:`_dist` plus the p99 tail — latency-shaped metrics (serving
    TTFT/ITL), where the tail IS the product claim."""
    s = sorted(v for v in vals if v is not None)
    if not s:
        return {}
    out = _dist(vals)
    out["p99"] = round(_percentile(s, 0.99), 3)
    return out


def attribution_rollup(rows: Sequence[Any]) -> Dict[str, Any]:
    """Aggregate per-request ``attribution`` dicts (the reqtrace shape:
    ``{"ttft": {...}, "itl": {...}}`` fraction dicts, each summing to
    1.0) into one wall-weighted rollup per class whose fractions STILL
    sum to 1.0 — the last sorted key absorbs the rounding residue, the
    same discipline :func:`apex_tpu.serve.reqtrace.attribution_fractions`
    applies per request. Lives here (not in serve) so journal analysis
    stays jax-free."""
    out: Dict[str, Any] = {}
    for cls in ("ttft", "itl"):
        frs = [(r.get(cls) or {}) for r in rows if isinstance(r, dict)]
        frs = [f for f in frs
               if isinstance(f.get("wall_s"), (int, float))
               and f["wall_s"] > 0]
        if not frs:
            continue
        walls = [float(f["wall_s"]) for f in frs]
        keys = sorted({k for f in frs for k in f if k.endswith("_frac")})
        if not keys:
            continue
        sums = {k: sum(float(f.get(k) or 0.0) * w
                       for f, w in zip(frs, walls)) for k in keys}
        norm = sum(sums.values()) or 1.0
        row: Dict[str, Any] = {
            "n": len(frs),
            "wall_s_mean": round(sum(walls) / len(walls), 6),
        }
        acc = 0.0
        for k in keys[:-1]:
            v = round(sums[k] / norm, 4)
            row[k] = v
            acc += v
        row[keys[-1]] = round(max(1.0 - acc, 0.0), 4)
        out[cls] = row
    return out


def _lstsq_slope(ys: List[float]) -> float:
    """Least-squares slope of ys over their indices (trend per record)."""
    n = len(ys)
    if n < 2:
        return 0.0
    xm = (n - 1) / 2.0
    ym = sum(ys) / n
    num = sum((i - xm) * (y - ym) for i, y in enumerate(ys))
    den = sum((i - xm) ** 2 for i in range(n))
    return num / den if den else 0.0


def load(path: str) -> List[Dict[str, Any]]:
    from apex_tpu.monitor.journal import MetricsJournal

    return MetricsJournal.read(path)


# ---------------------------------------------------------------------------
# analysis
# ---------------------------------------------------------------------------


def analyze(
    records: Sequence[Dict[str, Any]],
    *,
    stall_factor: float = 5.0,
    spike_factor: float = 3.0,
    spike_window: int = 16,
    max_list: int = 20,
) -> Dict[str, Any]:
    """Roll a journal up into the operator-facing health summary."""
    steps = [r for r in records if r.get("kind") == "step"]
    out: Dict[str, Any] = {
        "records": len(records),
        "step_records": len(steps),
        "truncated": bool(getattr(records, "truncated", False)),
        "bad_lines": int(getattr(records, "bad_lines", 0)),
    }
    meta = next((r for r in records if r.get("kind") == "meta"), None)
    if meta:
        out["meta"] = {k: v for k, v in meta.items()
                       if k not in ("v", "kind", "ts", "rank", "rank_info")}

    # throughput / wall-time percentiles
    rates = [r["tokens_per_sec"] for r in steps
             if isinstance(r.get("tokens_per_sec"), (int, float))]
    walls = [r["wall_s"] for r in steps
             if isinstance(r.get("wall_s"), (int, float))]
    if rates:
        out["tokens_per_sec"] = _dist(rates)
    if walls:
        out["wall_s"] = _dist(walls)

    # stall gaps: holes between consecutive step timestamps well beyond
    # the median cadence — the journal-side wedge/co-tenant signature
    ts = [(r.get("step", r.get("window")), r["ts"]) for r in steps
          if isinstance(r.get("ts"), (int, float))]
    gaps = [b[1] - a[1] for a, b in zip(ts, ts[1:])]
    med_gap = _median(gaps)
    stalls = []
    if med_gap and med_gap > 0:
        for (label, _), gap in zip(ts, gaps):
            if gap > stall_factor * med_gap:
                stalls.append({"after_step": label, "gap_s": round(gap, 3),
                               "x_median": round(gap / med_gap, 1)})
    out["stalls"] = {"median_cadence_s": round(med_gap, 3) if med_gap else None,
                     "count": len(stalls), "gaps": stalls[:max_list]}

    # loss spikes: rolling prior-window median baseline (same trigger
    # logic as diagnose.OverflowForensics), plus sanitized-NaN losses
    spikes, nonfinite = [], []
    history: List[float] = []
    for r in steps:
        label = r.get("step", r.get("window"))
        keys = r.get("nonfinite_keys") or []
        if any(k == "loss" or k.endswith(".loss") for k in keys):
            nonfinite.append(label)
            continue
        if r.get("found_inf"):
            # overflow steps never enter the spike baseline or spike
            # list — matching OverflowForensics, whose found_inf branch
            # wins over (and excludes the loss from) the spike trigger
            continue
        loss = r.get("loss")
        if not isinstance(loss, (int, float)):
            continue
        base = (_median(history[-spike_window:])
                if len(history) >= 4 else None)
        if base is not None and is_loss_spike(loss, base, spike_factor):
            spikes.append({"step": label, "loss": round(loss, 4),
                           "baseline": round(base, 4)})
        # spiked losses still enter the rolling baseline (matching
        # OverflowForensics): a sustained level shift flags a few steps
        # while the median catches up, then self-heals — it must not
        # brand every remaining step a spike
        history.append(loss)
    losses = [r["loss"] for r in steps
              if isinstance(r.get("loss"), (int, float))]
    out["loss"] = {
        "first": round(losses[0], 4) if losses else None,
        "last": round(losses[-1], 4) if losses else None,
        "spikes": spikes[:max_list], "spike_count": len(spikes),
        "nonfinite_steps": nonfinite[:max_list],
        "nonfinite_count": len(nonfinite),
    }

    # HBM trend: samples ride step records ("hbm" sub-dict) and
    # standalone kind="hbm" rows (HBMMonitor.sample)
    hbm = []
    for r in records:
        if r.get("kind") == "hbm" and isinstance(r.get("live_bytes"), (int, float)):
            hbm.append(r["live_bytes"])
        elif isinstance(r.get("hbm"), dict) and isinstance(
                r["hbm"].get("live_bytes"), (int, float)):
            hbm.append(r["hbm"]["live_bytes"])
    if hbm:
        out["hbm"] = {
            "samples": len(hbm),
            "first_bytes": int(hbm[0]), "last_bytes": int(hbm[-1]),
            "peak_bytes": int(max(hbm)),
            "growth_bytes": int(hbm[-1] - hbm[0]),
            "trend_bytes_per_sample": round(_lstsq_slope(hbm), 1),
        }

    # per-rank straggler skew: a rank whose median rate trails the
    # fastest marks the straggler (MPMD pipeline telemetry)
    by_rank: Dict[Any, List[float]] = {}
    for r in steps:
        if isinstance(r.get("tokens_per_sec"), (int, float)):
            by_rank.setdefault(r.get("rank", 0), []).append(r["tokens_per_sec"])
    if by_rank:
        rank_med = {rk: _median(v) for rk, v in by_rank.items()}
        fastest = max(rank_med.values())
        slowest_rank = min(rank_med, key=lambda rk: rank_med[rk])
        out["ranks"] = {
            "count": len(rank_med),
            "median_tokens_per_sec": {str(k): round(v, 1)
                                      for k, v in sorted(rank_med.items())},
            "straggler_rank": slowest_rank,
            "skew": (round(fastest / rank_med[slowest_rank], 3)
                     if rank_med[slowest_rank] else None),
        }

    # comm-bytes-per-axis rollup (rows carrying comm_bytes_by_axis —
    # scaling-harness configs, or meta records)
    comm: Dict[str, Dict[str, int]] = {}
    for r in records:
        table = r.get("comm_bytes_by_axis")
        if not isinstance(table, dict):
            continue
        for axis, row in table.items():
            agg = comm.setdefault(axis, {"bytes": 0, "calls": 0})
            agg["bytes"] += int(row.get("bytes", 0))
            agg["calls"] += int(row.get("calls", 0))
    if comm:
        out["comm_bytes_by_axis"] = comm

    # per-wire-dtype comm rollup (rows carrying comm_bytes_by_verb_dtype —
    # CommAccount.by_verb_dtype tables from quantized-collective configs):
    # a quantized reduce's int8 payload and its fp32 scale side-channel
    # land as distinct "<verb>[<dtype>]" rows, so the compression ratio
    # (and the side-channel's cost) read straight off the analysis
    comm_dt: Dict[str, Dict[str, int]] = {}
    for r in records:
        table = r.get("comm_bytes_by_verb_dtype")
        if not isinstance(table, dict):
            continue
        for key, row in table.items():
            agg = comm_dt.setdefault(key, {"bytes": 0, "calls": 0})
            agg["bytes"] += int(row.get("bytes", 0))
            agg["calls"] += int(row.get("calls", 0))
    if comm_dt:
        out["comm_bytes_by_verb_dtype"] = comm_dt

    # MFU / roofline summary (records journaled with step costs armed)
    mfus = [r["mfu"] for r in steps if isinstance(r.get("mfu"), (int, float))]
    if mfus:
        bw = [r["hbm_bw_util"] for r in steps
              if isinstance(r.get("hbm_bw_util"), (int, float))]
        bounds: Dict[str, int] = {}
        for r in steps:
            if r.get("bound"):
                bounds[r["bound"]] = bounds.get(r["bound"], 0) + 1
        out["mfu"] = dict(_dist(mfus), bound=bounds,
                          peak_source=next((r.get("peak_source") for r in steps
                                            if r.get("peak_source")), None))
        if bw:
            out["mfu"]["hbm_bw_util_p50"] = _dist(bw).get("p50")

    # timeline rollup (records from --trace-armed runs: bubble-fraction
    # stamps from the traced pipeline drive, anatomy fractions and
    # overlap from set_step_comm's step_anatomy join)
    tl: Dict[str, Any] = {}
    bub = [r["bubble_fraction"] for r in steps
           if isinstance(r.get("bubble_fraction"), (int, float))]
    if bub:
        tl["bubble_fraction"] = {"last": round(bub[-1], 4),
                                 "p50": _dist(bub).get("p50")}
        exp = next((r["bubble_fraction_expected"] for r in steps
                    if isinstance(r.get("bubble_fraction_expected"),
                                  (int, float))), None)
        if exp is not None:
            tl["bubble_fraction_expected"] = exp
    ovl = [r["overlap_fraction"] for r in steps
           if isinstance(r.get("overlap_fraction"), (int, float))]
    if ovl:
        tl["overlap_fraction"] = _dist(ovl)
    for key in ("compute_frac", "comm_frac", "stall_frac"):
        vals = [r[key] for r in steps
                if isinstance(r.get(key), (int, float))]
        if vals:
            tl[f"{key}_mean"] = round(sum(vals) / len(vals), 4)
    # per-link-class exposed comm (two-tier pod meshes: set_step_comm's
    # dcn_bytes_per_step arms ici_s/dcn_s stamps on every step record) —
    # `report compare --dcn-threshold` gates the dcn_s_p50 column
    tiers: Dict[str, Any] = {}
    for key in ("ici_s", "dcn_s"):
        vals = [r[key] for r in steps
                if isinstance(r.get(key), (int, float))]
        if vals:
            tiers[key] = _dist(vals)
    if tiers:
        tl["tiers"] = tiers
    if tl:
        out["timeline"] = tl

    # optimizer-state footprint (journals armed via set_opt_state_bytes —
    # the per-rank ZeRO claim: bytes/rank ÷ dp vs a replicated run)
    osb = [r["opt_state_bytes"] for r in steps
           if isinstance(r.get("opt_state_bytes"), (int, float))]
    if osb:
        out["opt_state_bytes"] = {"last": int(osb[-1]),
                                  "peak": int(max(osb))}

    # working-param footprint (set_param_bytes — the ZeRO-3 claim: the
    # bf16 params themselves at 1/dp vs a replicated run)
    pb = [r["param_bytes"] for r in steps
          if isinstance(r.get("param_bytes"), (int, float))]
    if pb:
        out["param_bytes"] = {"last": int(pb[-1]), "peak": int(max(pb))}

    # serving rollup (kind="request" records from apex_tpu.serve.Engine,
    # plus the queue/occupancy fields its decode ticks stamp on step
    # records): request latency in MILLISECONDS (journals carry seconds;
    # the 3-decimal rounding would erase sub-ms off-TPU latencies) with
    # the p99 tail — the serving product claim — and tokens/s/user from
    # each request's end-to-end time
    reqs = [r for r in records if r.get("kind") == "request"]
    if reqs:
        sv: Dict[str, Any] = {"requests": len(reqs)}
        ttft = [1e3 * r["ttft_s"] for r in reqs
                if isinstance(r.get("ttft_s"), (int, float))]
        itl = [1e3 * v for r in reqs for v in (r.get("itl_s") or [])
               if isinstance(v, (int, float))]
        if ttft:
            sv["ttft_ms"] = _dist_tail(ttft)
        if itl:
            sv["itl_ms"] = _dist_tail(itl)
        tps_user = [r["new_tokens"] / r["e2e_s"] for r in reqs
                    if isinstance(r.get("e2e_s"), (int, float))
                    and r["e2e_s"] > 0
                    and isinstance(r.get("new_tokens"), (int, float))]
        if tps_user:
            sv["tokens_per_sec_per_user"] = _dist(tps_user)
        qd = [r["queue_depth"] for r in steps
              if isinstance(r.get("queue_depth"), (int, float))]
        occ = [r["slot_occupancy"] for r in steps
               if isinstance(r.get("slot_occupancy"), (int, float))]
        if qd:
            sv["queue_depth"] = _dist(qd)
        if occ:
            sv["slot_occupancy"] = _dist(occ)
        # ISSUE 12 rollups — prefill records carry the prefix-sharing and
        # chunked-prefill evidence, step records the accepted draft length
        pf = [r for r in records if r.get("kind") == "prefill"]
        cached = [(r["cached_tokens"], r.get("prompt_len", 0)) for r in pf
                  if isinstance(r.get("cached_tokens"), (int, float))]
        if cached:
            tot_prompt = sum(p for _, p in cached)
            # token-level hit rate: the fraction of prompt tokens whose
            # prefill was SKIPPED by a cached prefix (the FLOPs claim)
            sv["prefix_hit_rate"] = round(
                sum(c for c, _ in cached) / tot_prompt, 4) if tot_prompt \
                else 0.0
            sv["pages_saved"] = int(sum(
                r.get("pages_shared", 0) for r in pf
                if isinstance(r.get("pages_shared"), (int, float))))
            sv["cow_forks"] = int(sum(
                r.get("cow_forks", 0) for r in pf
                if isinstance(r.get("cow_forks"), (int, float))))
        qdel = [1e3 * r["queue_delay_s"] for r in pf
                if isinstance(r.get("queue_delay_s"), (int, float))]
        if qdel:
            sv["prefill_queue_delay_ms"] = _dist(qdel)
        chunks = [r["chunks"] for r in pf
                  if isinstance(r.get("chunks"), (int, float))]
        if chunks:
            sv["prefill_chunks"] = int(sum(chunks))
        acc = [r["accepted_len"] for r in steps
               if isinstance(r.get("accepted_len"), (int, float))]
        if acc:
            sv["accepted_len"] = _dist(acc)
        # ISSUE 17: TTFT/ITL decomposed into queue / prefill-serialization
        # / compute / barrier fractions (wall-weighted over the request
        # records' per-request attribution dicts; each class sums to 1.0)
        attr = attribution_rollup([r.get("attribution") for r in reqs])
        if attr:
            sv["attribution"] = attr
        out["serving"] = sv

    # serve SLO windows (kind="slo" records from serve.Engine when
    # ServeConfig targets are set): per-window attainment — the fraction
    # of tokens inside their TTFT/ITL targets — plus goodput (in-SLO
    # tokens/s). Lives beside "serving" even for journals with slo rows
    # but no request records (crash-truncated runs).
    slo_rows = [r for r in records if r.get("kind") == "slo"]
    if slo_rows:
        att = [r["attainment"] for r in slo_rows
               if isinstance(r.get("attainment"), (int, float))]
        gp = [r["goodput_tokens_per_sec"] for r in slo_rows
              if isinstance(r.get("goodput_tokens_per_sec"), (int, float))]
        slo: Dict[str, Any] = {"windows": len(slo_rows)}
        if att:
            slo["attainment"] = _dist(att)
        if gp:
            slo["goodput_tokens_per_sec"] = _dist(gp)
        tgt = next((r.get("target") for r in slo_rows
                    if isinstance(r.get("target"), (int, float))), None)
        if tgt is not None:
            slo["target"] = tgt
        out["slo"] = slo

    # health alerts (monitor/health.py): the DERIVED count replays the
    # streaming rules over this journal (so the --max-alerts gate works
    # on journals that never armed a monitor); "journaled" counts the
    # kind="alert" rows an armed monitor wrote live. Always present, so
    # compare's alert check never skips on a clean run.
    try:
        from apex_tpu.monitor import health as health_mod

        derived = health_mod.scan(records)
        rollup = health_mod.summarize(derived)
    except Exception:  # noqa: BLE001 - analysis must survive a bad journal
        derived, rollup = [], {"count": 0, "by_rule": {}}
    out["alerts"] = dict(
        rollup,
        journaled=sum(1 for r in records if r.get("kind") == "alert"),
        list=derived[:max_list],
    )

    # overflow / forensics / recompile rollups
    overflows = [r["overflows"] for r in steps
                 if isinstance(r.get("overflows"), (int, float))]
    out["overflows"] = int(max(overflows)) if overflows else 0
    forensics = [r for r in records if r.get("kind") == "forensics"]
    if forensics:
        by_trigger: Dict[str, int] = {}
        for r in forensics:
            by_trigger[r.get("trigger", "?")] = (
                by_trigger.get(r.get("trigger", "?"), 0) + 1)
        out["forensics"] = {
            "count": len(forensics), "by_trigger": by_trigger,
            "nonfinite_groups": sorted({g for r in forensics
                                        for g in r.get("nonfinite_groups", [])}),
        }
    recompiles = [r for r in records if r.get("kind") == "recompile"]
    if recompiles:
        by_fn: Dict[str, Dict[str, Any]] = {}
        for r in recompiles:
            row = by_fn.setdefault(r.get("fn", "?"),
                                   {"compiles": 0, "compile_s": 0.0,
                                    "signatures": set()})
            row["compiles"] += 1
            row["compile_s"] += float(r.get("compile_s", 0.0))
            row["signatures"].add(r.get("signature", ""))
        out["recompiles"] = {
            fn: {"compiles": v["compiles"],
                 "compile_s": round(v["compile_s"], 3),
                 "signatures": len(v["signatures"])}
            for fn, v in by_fn.items()}
    return out


def render(analysis: Dict[str, Any], file=None) -> None:
    """Human-readable view of :func:`analyze` (the JSON is the API)."""
    file = file or sys.stdout
    p = lambda *a: print(*a, file=file)  # noqa: E731
    p(f"records: {analysis['records']} "
      f"(steps: {analysis['step_records']}"
      + (", TRUNCATED final line" if analysis["truncated"] else "")
      + (f", {analysis['bad_lines']} bad line(s)" if analysis["bad_lines"] else "")
      + ")")
    meta = analysis.get("meta")
    if meta:
        env = meta.get("env") or {}
        bits = []
        if meta.get("run"):
            bits.append(f"run {meta['run']}")
        if meta.get("fingerprint"):
            bits.append(f"fingerprint {meta['fingerprint']}")
        if env.get("git"):
            bits.append(f"git {env['git']}")
        if env.get("jax"):
            bits.append(f"jax {env['jax']}")
        if env.get("device_platform"):
            bits.append(env["device_platform"])
        if env.get("peak_overrides"):
            bits.append("peak overrides "
                        + ",".join(sorted(env["peak_overrides"])))
        if bits:
            p("meta: " + "  ".join(bits))
    tp = analysis.get("tokens_per_sec")
    if tp:
        p(f"throughput tok/s: p10 {tp['p10']}  p50 {tp['p50']}  "
          f"p90 {tp['p90']}  (min {tp['min']}, max {tp['max']}, n={tp['n']})")
    mfu = analysis.get("mfu")
    if mfu:
        p(f"mfu: p50 {mfu.get('p50')}  (min {mfu.get('min')}, max "
          f"{mfu.get('max')}; bound {mfu.get('bound')}; "
          f"hbm_bw_util p50 {mfu.get('hbm_bw_util_p50')}; "
          f"peak source {mfu.get('peak_source')})")
    st = analysis.get("stalls", {})
    p(f"stalls: {st.get('count', 0)} "
      f"(median cadence {st.get('median_cadence_s')}s)")
    for g in st.get("gaps", []):
        p(f"  after step {g['after_step']}: {g['gap_s']}s "
          f"({g['x_median']}x median)")
    lo = analysis.get("loss", {})
    p(f"loss: first {lo.get('first')} -> last {lo.get('last')}; "
      f"{lo.get('spike_count', 0)} spike(s), "
      f"{lo.get('nonfinite_count', 0)} non-finite")
    for s in lo.get("spikes", []):
        p(f"  spike at step {s['step']}: {s['loss']} "
          f"(baseline {s['baseline']})")
    hbm = analysis.get("hbm")
    if hbm:
        p(f"hbm: growth {hbm['growth_bytes'] / 1e6:.1f} MB over "
          f"{hbm['samples']} samples (peak {hbm['peak_bytes'] / 1e6:.1f} MB, "
          f"trend {hbm['trend_bytes_per_sample'] / 1e6:.2f} MB/sample)")
    rk = analysis.get("ranks")
    if rk and rk["count"] > 1:
        p(f"ranks: {rk['count']}, straggler rank {rk['straggler_rank']} "
          f"(skew {rk['skew']}x)")
    comm = analysis.get("comm_bytes_by_axis")
    if comm:
        for axis, row in sorted(comm.items()):
            p(f"comm[{axis}]: {row['bytes'] / 1e6:.2f} MB over "
              f"{row['calls']} call site(s)")
    comm_dt = analysis.get("comm_bytes_by_verb_dtype")
    if comm_dt:
        for key, row in sorted(comm_dt.items()):
            p(f"comm {key}: {row['bytes'] / 1e6:.2f} MB over "
              f"{row['calls']} call site(s)")
    tl = analysis.get("timeline")
    if tl:
        bf = tl.get("bubble_fraction") or {}
        parts = []
        if bf:
            exp = tl.get("bubble_fraction_expected")
            parts.append(f"bubble p50 {bf.get('p50')}"
                         + (f" (analytic floor {exp})"
                            if exp is not None else ""))
        if tl.get("overlap_fraction"):
            parts.append(f"overlap p50 {tl['overlap_fraction'].get('p50')}")
        fr = [f"{k[:-10]} {tl[k]}" for k in
              ("compute_frac_mean", "comm_frac_mean", "stall_frac_mean")
              if k in tl]
        if fr:
            parts.append("anatomy " + "/".join(fr))
        tiers = tl.get("tiers") or {}
        if tiers:
            parts.append("exposed comm " + " ".join(
                f"{k[:-2]} p50 {tiers[k].get('p50')}s"
                for k in ("ici_s", "dcn_s") if k in tiers))
        p("timeline: " + "; ".join(parts))
    osb = analysis.get("opt_state_bytes")
    if osb:
        p(f"opt state: {osb['last'] / 1e6:.1f} MB/rank "
          f"(peak {osb['peak'] / 1e6:.1f} MB)")
    pb = analysis.get("param_bytes")
    if pb:
        p(f"params: {pb['last'] / 1e6:.1f} MB/rank "
          f"(peak {pb['peak'] / 1e6:.1f} MB)")
    sv = analysis.get("serving")
    if sv:
        parts = [f"{sv['requests']} request(s)"]
        if sv.get("ttft_ms"):
            parts.append(f"ttft p50 {sv['ttft_ms']['p50']}ms "
                         f"p99 {sv['ttft_ms']['p99']}ms")
        if sv.get("itl_ms"):
            parts.append(f"itl p50 {sv['itl_ms']['p50']}ms "
                         f"p99 {sv['itl_ms']['p99']}ms")
        if sv.get("tokens_per_sec_per_user"):
            parts.append(
                f"tok/s/user p50 {sv['tokens_per_sec_per_user']['p50']}")
        if sv.get("queue_depth"):
            parts.append(f"queue p50 {sv['queue_depth']['p50']}")
        if sv.get("slot_occupancy"):
            parts.append(f"occupancy p50 {sv['slot_occupancy']['p50']}")
        if sv.get("prefix_hit_rate") is not None:
            parts.append(f"prefix hit-rate {sv['prefix_hit_rate']} "
                         f"({sv.get('pages_saved', 0)} page(s) shared, "
                         f"{sv.get('cow_forks', 0)} COW fork(s))")
        if sv.get("prefill_queue_delay_ms"):
            parts.append(
                f"prefill queue delay p50 "
                f"{sv['prefill_queue_delay_ms']['p50']}ms")
        if sv.get("accepted_len"):
            parts.append(f"accepted draft len p50 "
                         f"{sv['accepted_len']['p50']}")
        p("serving: " + "; ".join(parts))
        attr = sv.get("attribution") or {}
        for cls in ("ttft", "itl"):
            row = attr.get(cls)
            if row:
                fr = ", ".join(
                    f"{k[:-5]} {row[k]}" for k in sorted(row)
                    if k.endswith("_frac"))
                p(f"  {cls} attribution (n={row['n']}, "
                  f"wall mean {row['wall_s_mean']}s): {fr}")
    slo = analysis.get("slo")
    if slo:
        att = slo.get("attainment") or {}
        gp = slo.get("goodput_tokens_per_sec") or {}
        p(f"slo: {slo['windows']} window(s), attainment p50 "
          f"{att.get('p50')} (min {att.get('min')}"
          + (f", target {slo['target']}" if slo.get("target") is not None
             else "")
          + (f"), goodput p50 {gp.get('p50')} tok/s" if gp else ")"))
    al = analysis.get("alerts")
    if al:
        rules = ", ".join(f"{k}: {v}"
                          for k, v in sorted(al["by_rule"].items()))
        live = (f"; {al['journaled']} journaled live"
                if al.get("journaled") else "")
        p(f"alerts: {al['count']} ({rules or 'none'}{live})")
        for a in al.get("list", [])[:8]:
            p(f"  [{a['rule']}] step {a.get('step')}: {a.get('message')}")
    p(f"overflows: {analysis.get('overflows', 0)}")
    fo = analysis.get("forensics")
    if fo:
        p(f"forensics: {fo['count']} record(s) {fo['by_trigger']}"
          + (f", non-finite groups: {fo['nonfinite_groups']}"
             if fo["nonfinite_groups"] else ""))
    rc = analysis.get("recompiles")
    if rc:
        for fn, row in sorted(rc.items()):
            p(f"recompiles[{fn}]: {row['compiles']} "
              f"({row['compile_s']}s, {row['signatures']} signature(s))")


# ---------------------------------------------------------------------------
# compare (the machine regression gate)
# ---------------------------------------------------------------------------


def must_not_drop(threshold: float):
    """Shared fractional-drop predicate: B regresses iff it falls more
    than ``threshold`` below A (throughput/MFU-shaped metrics)."""
    return lambda va, vb: vb < va * (1.0 - threshold)


def must_not_grow(threshold: float, slack: float = 0.0):
    """Shared fractional-growth predicate: B regresses iff it exceeds A
    by more than ``threshold`` (plus an absolute ``slack`` floor for
    near-zero baselines — a 0.001 bubble must not gate on timer noise).
    Residency-bytes and bubble-fraction-shaped metrics."""
    return lambda va, vb: vb > va * (1.0 + threshold) + slack


def compare(
    a: Sequence[Dict[str, Any]],
    b: Sequence[Dict[str, Any]],
    *,
    threshold: float = 0.05,
    hbm_slack_bytes: int = 64 << 20,
    loss_threshold: Optional[float] = None,
    bubble_threshold: Optional[float] = None,
    overlap_threshold: Optional[float] = None,
    dcn_threshold: Optional[float] = None,
    max_alerts: Optional[int] = None,
) -> Dict[str, Any]:
    """Compare run B against baseline A; ``regressed`` iff B is worse.

    Checks (each skipped when either side lacks the signal): B must have
    step records when A did; p50 throughput and p50 MFU must not drop by
    more than ``threshold`` (fractional; MFU compared only when both
    runs share a peak-spec provenance); the per-step overflow rate must
    not more than double past a 1%-of-steps floor; HBM growth must not
    exceed A's by more than ``hbm_slack_bytes``; B must not introduce
    non-finite losses A did not have; the per-rank ``opt_state_bytes``/
    ``param_bytes`` stamps must not grow past the threshold (a candidate
    that silently dropped ZeRO/ZeRO-3 re-replicates O(model) state at
    identical throughput — only these stamps would see it).

    ``loss_threshold`` (off by default — timing gates must not fail on
    stochastic loss noise) arms the CONVERGENCE check: B's final loss
    must not exceed A's by more than this fraction of A's loss drop
    (``first - last``; falls back to ``|last|`` when A never improved).
    Scaling by the drop makes the tolerance mean "fraction of the
    learning progress given back" — the machine gate for paired
    fp32-wire vs quantized-wire training runs (the quantized-collectives
    convergence bar, parallel/quantize.py).

    ``overlap_threshold`` tunes the comm/compute OVERLAP gate (defaults
    to ``threshold`` when journals carry ``overlap_fraction`` stamps —
    ``set_step_comm``'s step-anatomy join): B's overlap fraction must not
    DROP past it — the machine gate for structural-prefetch work (the
    ZeRO-3 double-buffered gathers whose win IS the overlap fraction,
    ``models/_transformer._prefetched_zero3_drive``), sharing the same
    :func:`must_not_drop` predicate as throughput.

    ``dcn_threshold`` tunes the per-tier exposed-comm gate (defaults to
    ``threshold`` when journals carry ``dcn_s`` stamps — two-tier pod
    journals armed via ``set_step_comm(dcn_bytes_per_step=...)``): B's
    exposed DCN seconds p50 must not GROW past it (+1 ms slack) — the
    machine gate for hierarchical-collective work
    (``parallel/hierarchy.py``), sharing :func:`must_not_grow`.

    Serving journals (``kind="request"`` records from ``apex_tpu.serve``)
    gate symmetrically: B must still serve requests when A did, TTFT/ITL
    p50 must not grow past ``threshold`` (+0.05 ms timer-noise slack), and
    per-user tokens/s must not drop — the latency-shaped regression gate
    ISSUE 10's satellite adds. ISSUE 12 extends them: the ITL p99 TAIL
    must not grow (+0.5 ms slack — the monolithic-long-prompt stall the
    chunked prefill exists to remove lives in the tail), and the prefix
    hit-rate / mean accepted draft length (``kind="prefill"`` and step
    ``accepted_len`` stamps) must not DROP — the same
    :func:`must_not_drop` predicate throughput uses. ISSUE 17 adds the
    attribution gates (``ttft_queue_frac``/``itl_queue_frac`` must not
    grow — the queue share of each latency class, from the request
    records' per-request attribution) and degrades the mixed serve/train
    pair gracefully: when exactly one journal has serving records and
    the other is a train journal, the serving gates are skipped with a
    note instead of failing.

    ``max_alerts`` (off by default) arms the health-alert gate: the
    candidate's derived alert count (``monitor/health.py`` rules replayed
    over the journal by ``analyze``) may not exceed the budget nor the
    baseline's own count — so a self-compare always passes and a noisy
    baseline never fails its identical twin.

    ``bubble_threshold`` tunes the pipeline bubble-fraction gate
    independently of ``threshold`` (it defaults to ``threshold`` when
    journals carry ``bubble_fraction`` stamps): B's bubble fraction must
    not grow past it — the machine before/after for schedule work
    (ROADMAP item 5; the analytic floor rides the journal as
    ``bubble_fraction_expected``). All fractional tolerances share one
    predicate pair (:func:`must_not_drop` / :func:`must_not_grow`).
    """
    ra, rb = analyze(a), analyze(b)
    checks: List[Dict[str, Any]] = []

    def check(name, va, vb, *, worse):
        if va is None or vb is None:
            return
        checks.append({"check": name, "a": va, "b": vb,
                       "regressed": bool(worse(va, vb))})

    # structural gate FIRST: a candidate that journaled nothing (crashed
    # before its first step record) must FAIL, not skip every signal
    # check and sail through green
    check("step_records", ra["step_records"], rb["step_records"],
          worse=lambda va, vb: va > 0 and vb == 0)
    check("tokens_per_sec_p50",
          (ra.get("tokens_per_sec") or {}).get("p50"),
          (rb.get("tokens_per_sec") or {}).get("p50"),
          worse=must_not_drop(threshold))
    # MFU is only comparable against the SAME peak denominator: a
    # baseline armed with an env-calibrated ceiling vs a candidate on
    # the datasheet row would regress ~4x at identical throughput
    src_a = (ra.get("mfu") or {}).get("peak_source")
    src_b = (rb.get("mfu") or {}).get("peak_source")
    if src_a == src_b:
        check("mfu_p50",
              (ra.get("mfu") or {}).get("p50"),
              (rb.get("mfu") or {}).get("p50"),
              worse=must_not_drop(threshold))
    else:
        checks.append({"check": "mfu_p50", "a": src_a, "b": src_b,
                       "regressed": False,
                       "skipped": "peak_source mismatch"})
    # overflow comparison is per-step (a longer healthy run accumulates
    # more warmup overflows at the same rate); regression = the rate
    # more than doubles past a 1%-of-steps floor
    rate = lambda r: (r["overflows"] / r["step_records"]  # noqa: E731
                      if r["step_records"] else 0.0)
    check("overflow_rate", round(rate(ra), 4), round(rate(rb), 4),
          worse=lambda va, vb: vb > 2.0 * va + 0.01)
    check("hbm_growth_bytes",
          (ra.get("hbm") or {}).get("growth_bytes"),
          (rb.get("hbm") or {}).get("growth_bytes"),
          worse=lambda va, vb: vb > va + hbm_slack_bytes)
    check("nonfinite_losses",
          (ra.get("loss") or {}).get("nonfinite_count", 0),
          (rb.get("loss") or {}).get("nonfinite_count", 0),
          worse=lambda va, vb: vb > va)
    if loss_threshold is not None:
        # convergence gate: final loss within loss_threshold x A's loss
        # drop (docstring) — the tolerance is denominated in learning
        # progress, so short runs with small absolute drops gate tightly
        la = ra.get("loss") or {}
        drop = None
        if isinstance(la.get("first"), (int, float)) and isinstance(
                la.get("last"), (int, float)):
            drop = la["first"] - la["last"]
            if drop <= 0:
                drop = abs(la["last"]) or 1.0
        check("loss_last", la.get("last"),
              (rb.get("loss") or {}).get("last"),
              worse=lambda va, vb: vb > va + loss_threshold * (
                  drop if drop is not None else abs(va) or 1.0))
    # per-rank residency stamps (set_opt_state_bytes/set_param_bytes):
    # regression = the static footprint GROWS past the threshold — a
    # candidate that quietly dropped ZeRO(-3) re-replicates O(model)
    # state at identical throughput, which no other check would see
    check("opt_state_bytes_last",
          (ra.get("opt_state_bytes") or {}).get("last"),
          (rb.get("opt_state_bytes") or {}).get("last"),
          worse=must_not_grow(threshold))
    check("param_bytes_last",
          (ra.get("param_bytes") or {}).get("last"),
          (rb.get("param_bytes") or {}).get("last"),
          worse=must_not_grow(threshold))
    # pipeline bubble fraction (journals stamped by set_bubble_fraction):
    # regression = the measured bubble GROWS past the tolerance — the
    # machine gate schedule rewrites are judged by. The 0.01 absolute
    # slack keeps near-zero-bubble baselines from gating on timer noise.
    check("bubble_fraction_p50",
          ((ra.get("timeline") or {}).get("bubble_fraction") or {}).get("p50"),
          ((rb.get("timeline") or {}).get("bubble_fraction") or {}).get("p50"),
          worse=must_not_grow(
              threshold if bubble_threshold is None else bubble_threshold,
              slack=0.01))
    # comm/compute overlap fraction (set_step_comm's step-anatomy join):
    # regression = the measured overlap DROPS past the tolerance — the
    # machine gate for structural-prefetch work (ZeRO-3 double-buffered
    # gathers); higher is better, so the drop predicate
    check("overlap_fraction_p50",
          ((ra.get("timeline") or {}).get("overlap_fraction") or {}).get("p50"),
          ((rb.get("timeline") or {}).get("overlap_fraction") or {}).get("p50"),
          worse=must_not_drop(
              threshold if overlap_threshold is None else overlap_threshold))
    # per-tier exposed comm (two-tier pod meshes, set_step_comm's
    # dcn_bytes_per_step arm): the DCN leg is the scarce wire — a
    # candidate whose exposed dcn_s GROWS past the tolerance regressed
    # the hierarchical decomposition (e.g. a flat cross-island reduce
    # slipped back in). 1 ms absolute slack for timer noise.
    check("dcn_s_p50",
          (((ra.get("timeline") or {}).get("tiers") or {})
           .get("dcn_s") or {}).get("p50"),
          (((rb.get("timeline") or {}).get("tiers") or {})
           .get("dcn_s") or {}).get("p50"),
          worse=must_not_grow(
              threshold if dcn_threshold is None else dcn_threshold,
              slack=0.001))
    # serving latency gates (kind="request" journals from the serve
    # engine): TTFT/ITL p50 must not GROW past the threshold — the same
    # machine gate training throughput gets, pointed at the latency-shaped
    # metrics (lower is better, so the growth predicate). The 0.05 ms
    # absolute slack keeps tiny off-TPU runs from gating on timer noise.
    sva = ra.get("serving") or {}
    svb = rb.get("serving") or {}
    # mixed serve/train pair (ISSUE 17 satellite): when exactly one side
    # served and the serve-less side is a TRAIN journal (it has loss
    # records — a crashed serve candidate has neither), the pair is mixed
    # on purpose; note it and skip the serving gates instead of erroring
    # or failing the crash guard below
    if bool(sva.get("requests")) != bool(svb.get("requests")):
        other = rb if sva.get("requests") else ra
        which = "b" if sva.get("requests") else "a"
        if ((other.get("loss") or {}).get("first")) is not None:
            checks.append({
                "check": "serve_requests",
                "a": sva.get("requests", 0), "b": svb.get("requests", 0),
                "regressed": False,
                "skipped": f"no serving records in {which} (train journal)",
            })
            sva, svb = {}, {}  # every serving check below skips on None
    # a candidate that served NOTHING has no "serving" section at all —
    # default its count to 0 (not None, which would skip the check and
    # sail a crashed candidate through green) whenever A served requests
    check("serve_requests", sva.get("requests"),
          svb.get("requests", 0) if sva.get("requests") else
          svb.get("requests"),
          worse=lambda va, vb: va > 0 and vb == 0)
    for key in ("ttft_ms", "itl_ms"):
        check(f"{key}_p50",
              (sva.get(key) or {}).get("p50"),
              (svb.get(key) or {}).get("p50"),
              worse=must_not_grow(threshold, slack=0.05))
    # the ITL TAIL gates too (ISSUE 12): a monolithic long-prompt prefill
    # stalls every running stream for the whole prompt — a p99 spike the
    # p50 can hide when only a few samples land in the stall. Larger
    # absolute slack: the tail of a tiny off-TPU run is timer-noisy.
    check("itl_ms_p99",
          (sva.get("itl_ms") or {}).get("p99"),
          (svb.get("itl_ms") or {}).get("p99"),
          worse=must_not_grow(threshold, slack=0.5))
    check("tokens_per_sec_per_user_p50",
          (sva.get("tokens_per_sec_per_user") or {}).get("p50"),
          (svb.get("tokens_per_sec_per_user") or {}).get("p50"),
          worse=must_not_drop(threshold))
    # prefix-sharing / speculative-decoding regression gates (ISSUE 12):
    # the prefix hit-rate and the mean accepted draft length are
    # higher-is-better — a candidate that silently dropped sharing or
    # whose draft stopped agreeing regresses through the SAME
    # must_not_drop predicate throughput uses
    check("prefix_hit_rate", sva.get("prefix_hit_rate"),
          svb.get("prefix_hit_rate"),
          worse=must_not_drop(threshold))
    check("accepted_len_p50",
          (sva.get("accepted_len") or {}).get("p50"),
          (svb.get("accepted_len") or {}).get("p50"),
          worse=must_not_drop(threshold))
    # latency ATTRIBUTION gates (ISSUE 17): the queue fraction of each
    # request class must not GROW — a candidate whose TTFT held steady by
    # trading compute for admission wait is a scheduling regression the
    # raw percentiles can hide. Same predicate family; the 0.05 absolute
    # slack covers near-zero-queue baselines.
    for cls in ("ttft", "itl"):
        check(f"{cls}_queue_frac",
              ((sva.get("attribution") or {}).get(cls) or {}).get(
                  "queue_frac"),
              ((svb.get("attribution") or {}).get(cls) or {}).get(
                  "queue_frac"),
              worse=must_not_grow(threshold, slack=0.05))
    # serve SLO attainment (kind="slo" window records): the fraction of
    # tokens inside their latency targets must not DROP — the serving
    # health twin of the throughput gate
    check("slo_attainment_p50",
          ((ra.get("slo") or {}).get("attainment") or {}).get("p50"),
          ((rb.get("slo") or {}).get("attainment") or {}).get("p50"),
          worse=must_not_drop(threshold))
    if max_alerts is not None:
        # health-alert gate (--max-alerts): the candidate's DERIVED alert
        # count (health.scan — works on journals that never armed a live
        # monitor) may not exceed the budget nor the baseline's own count
        # (a noisy baseline doesn't fail its twin; self-compare always
        # passes)
        check("alerts",
              (ra.get("alerts") or {}).get("count", 0),
              (rb.get("alerts") or {}).get("count", 0),
              worse=lambda va, vb: vb > max(va, max_alerts))
    regressed = [c["check"] for c in checks if c["regressed"]]
    return {"threshold": threshold, "checks": checks,
            "regressed": regressed, "ok": not regressed,
            "a": {"step_records": ra["step_records"]},
            "b": {"step_records": rb["step_records"]}}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "compare":
        p = argparse.ArgumentParser(
            prog="python -m apex_tpu.monitor.report compare",
            description="Regression gate between two journals "
                        "(exit 1 on regression).")
        p.add_argument("baseline")
        p.add_argument("candidate")
        p.add_argument("--threshold", type=float, default=0.05,
                       help="max fractional drop in p50 throughput/MFU "
                            "(default 0.05)")
        p.add_argument("--hbm-slack-mb", type=float, default=64.0,
                       help="allowed HBM-growth excess over baseline (MiB)")
        p.add_argument("--loss-threshold", type=float, default=None,
                       help="arm the convergence gate: candidate final loss "
                            "must be within this fraction of the baseline's "
                            "loss drop (off by default — see compare())")
        p.add_argument("--bubble-threshold", type=float, default=None,
                       help="max fractional growth in the pipeline bubble "
                            "fraction (defaults to --threshold when "
                            "journals carry bubble_fraction stamps)")
        p.add_argument("--overlap-threshold", type=float, default=None,
                       help="max fractional DROP in the comm/compute "
                            "overlap fraction (defaults to --threshold "
                            "when journals carry overlap_fraction stamps "
                            "— the structural-prefetch gate)")
        p.add_argument("--dcn-threshold", type=float, default=None,
                       help="max fractional GROWTH in exposed DCN comm "
                            "seconds p50 (defaults to --threshold when "
                            "journals carry dcn_s stamps — the two-tier "
                            "pod hierarchical-collective gate)")
        p.add_argument("--max-alerts", type=int, default=None,
                       help="arm the health-alert gate: the candidate's "
                            "derived alert count (monitor/health.py rules "
                            "replayed over the journal) may not exceed "
                            "this budget nor the baseline's own count")
        p.add_argument("--json", action="store_true",
                       help="print the full comparison as one JSON object")
        p.add_argument("--format", choices=("text", "json"), default=None,
                       help="output format (json == --json; parity with "
                            "`python -m apex_tpu.lint --format json`)")
        args = p.parse_args(argv[1:])
        res = compare(load(args.baseline), load(args.candidate),
                      threshold=args.threshold,
                      # MiB, matching compare()'s 64 << 20 default exactly
                      hbm_slack_bytes=int(args.hbm_slack_mb * (1 << 20)),
                      loss_threshold=args.loss_threshold,
                      bubble_threshold=args.bubble_threshold,
                      overlap_threshold=args.overlap_threshold,
                      dcn_threshold=args.dcn_threshold,
                      max_alerts=args.max_alerts)
        if args.json or args.format == "json":
            print(json.dumps(res))
        else:
            for c in res["checks"]:
                mark = "REGRESSED" if c["regressed"] else "ok"
                print(f"{c['check']:<22} A={c['a']} B={c['b']}  {mark}")
            print("REGRESSION: " + ", ".join(res["regressed"])
                  if res["regressed"] else "no regression")
        return 0 if res["ok"] else 1

    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.monitor.report",
        description=(
            "Analyze a MetricsJournal JSON-lines file (or: "
            "'compare <A> <B>' for the regression gate)."))
    p.add_argument("journal")
    p.add_argument("--json", action="store_true",
                   help="print the analysis as one JSON object")
    p.add_argument("--format", choices=("text", "json"), default=None,
                   help="output format: json emits the full rollup as one "
                        "JSON object (same as --json; parity with "
                        "`python -m apex_tpu.lint --format json`, so "
                        "CI/driver consumers stop scraping text)")
    p.add_argument("--stall-factor", type=float, default=5.0)
    p.add_argument("--spike-factor", type=float, default=3.0)
    args = p.parse_args(argv)
    analysis = analyze(load(args.journal), stall_factor=args.stall_factor,
                       spike_factor=args.spike_factor)
    if args.json or args.format == "json":
        print(json.dumps(analysis))
    else:
        render(analysis)
    return 0


if __name__ == "__main__":
    sys.exit(main())
