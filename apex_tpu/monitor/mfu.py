"""MFU / roofline reporting: achieved vs peak FLOP/s and HBM bandwidth.

The journal (``monitor/journal.py``) records what a step DID (tokens/s,
wall time); this module records what the chip COULD have done, so every
journal window carries a utilization verdict instead of a raw rate:

- ``mfu``: achieved FLOP/s over the platform's peak — the model-FLOPs
  utilization number veScale-style eager-SPMD systems report per step
  (PAPERS.md, arxiv 2509.07003) and PERF_NOTES argues by hand for the
  345M headline (17.4 TFLOP / 257.7 ms = 67.5 TF/s against the 71-78
  TF/s this tunnel chip sustains).
- ``hbm_bw_util``: achieved bytes/s over peak HBM bandwidth.
- ``bound``: the roofline verdict — whichever of the two time floors
  (flops/peak_flops vs bytes/peak_bw) dominates is what the step is
  limited by; ties within 10% report ``"balanced"``.

FLOPs/bytes come from the pyprof cost layer (``pyprof.cost_analysis`` /
``per_scope_costs``): :func:`compiled_step_costs` reads the XLA cost
model off a compiled executable (taking ``max`` with the jaxpr count
when given — the cost model sees zero FLOPs inside Pallas custom-calls,
pyprof.profile_fn's documented undercount), and :func:`traced_step_costs`
needs only a trace (no compile) — its bytes are algorithmic
operand+result sizes (pre-fusion upper bound), flagged by ``method``.

Peak specs: a small per-platform table (public bf16 peak / HBM BW per
TPU generation), overridable via ``APEX_TPU_PEAK_FLOPS`` /
``APEX_TPU_PEAK_HBM_GBPS`` — through the axon tunnel the honest
denominator is the chip's measured sustained ceiling (71-78 TF/s on
chained matmuls, PERF_NOTES), not the datasheet, so the env override is
the production path there. Every record names its spec ``source`` so an
env-calibrated mfu is never confused with a datasheet one.

All host-side and trace-time only: nothing here touches the hot path,
and programs compiled with reporting disabled are byte-identical.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

ENV_PEAK_FLOPS = "APEX_TPU_PEAK_FLOPS"
ENV_PEAK_HBM_GBPS = "APEX_TPU_PEAK_HBM_GBPS"

#: platform substring -> (peak bf16 FLOP/s, peak HBM bytes/s). Public
#: datasheet numbers; matched case-insensitively against device_kind so
#: "TPU v5 lite" and "tpu v5e" both land on the v5e row.
PEAK_SPECS = {
    "v6e": (918e12, 1640e9),
    "v6": (918e12, 1640e9),
    "v5p": (459e12, 2765e9),
    "v5e": (197e12, 819e9),
    "v5 lite": (197e12, 819e9),
    "v4": (275e12, 1228e9),
    "v3": (123e12, 900e9),
    "v2": (45e12, 700e9),
    # CPU rows exist so the virtual-mesh CI path produces *labelled*
    # numbers (source="table:cpu") rather than crashing; they are
    # order-of-magnitude host figures, not measurements.
    "cpu": (2e11, 50e9),
}

#: unknown accelerator fallback (flagged source="fallback"): v4-class.
_FALLBACK = (275e12, 1228e9)


def _detect_platform() -> str:
    try:
        import jax

        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", "") or ""
        return f"{dev.platform} {kind}".strip()
    except Exception:  # noqa: BLE001 - no backend: stay host-side
        return "unknown"


def peak_spec(platform: Optional[str] = None) -> Dict[str, Any]:
    """Resolve ``{platform, peak_flops, peak_hbm_bytes_per_sec, source}``.

    Env overrides win (``APEX_TPU_PEAK_FLOPS`` in FLOP/s,
    ``APEX_TPU_PEAK_HBM_GBPS`` in decimal GB/s — the tunnel-calibration
    knobs, PERF_NOTES "Peak-spec table"); otherwise the table row whose
    key is a substring of the platform string; otherwise the flagged
    fallback.
    """
    plat = (platform or _detect_platform()).lower()
    flops, bw, source = None, None, None
    for key, (f, b) in PEAK_SPECS.items():
        if key in plat:
            flops, bw, source = f, b, f"table:{key}"
            break
    if flops is None:
        flops, bw, source = _FALLBACK[0], _FALLBACK[1], "fallback"
    # per-knob overrides with per-knob provenance: overriding only the
    # FLOP ceiling must not stamp the datasheet HBM number "env" (and a
    # malformed value in one knob must not discard the other's)
    src_f = src_b = source
    try:
        env_f = os.environ.get(ENV_PEAK_FLOPS)
        if env_f:
            flops, src_f = float(env_f), "env"
    except ValueError:
        pass  # malformed override: keep the table row
    try:
        env_b = os.environ.get(ENV_PEAK_HBM_GBPS)
        if env_b:
            bw, src_b = float(env_b) * 1e9, "env"
    except ValueError:
        pass
    # an armed calibration file (APEX_TPU_CALIBRATION) outranks the env
    # knobs: a constant fitted from this machine's measured runs beats a
    # hand-typed one. Disarmed (env var unset): nothing changes.
    try:
        from apex_tpu.monitor import calibrate as _calibrate

        cal = _calibrate.active()
    except Exception:  # noqa: BLE001 - calibration is best-effort
        cal = None
    if cal:
        cf = cal.get("peak_flops")
        if isinstance(cf, (int, float)) and cf > 0:
            flops, src_f = float(cf), "calibrated"
        cb = cal.get("peak_hbm_bytes_per_sec")
        if isinstance(cb, (int, float)) and cb > 0:
            bw, src_b = float(cb), "calibrated"
    source = src_f if src_f == src_b else f"flops:{src_f}|hbm:{src_b}"
    return {"platform": plat, "peak_flops": flops,
            "peak_hbm_bytes_per_sec": bw, "source": source}


def modeled_compute_seconds(
    flops: float,
    *,
    spec: Optional[Dict[str, Any]] = None,
    platform: Optional[str] = None,
) -> float:
    """Compute-time floor of ``flops`` against the resolved peak spec.

    The planner's (``apex_tpu.plan``) compute leg: honors the same
    calibrated > env > table > fallback precedence as :func:`peak_spec`,
    so an armed ``APEX_TPU_CALIBRATION`` file closes the
    predicted-vs-measured loop with no planner-side knobs. Returns
    ``inf`` when the spec resolves no FLOP ceiling (nothing to divide
    by — an infeasible time floor, never a silent 0).
    """
    spec = spec or peak_spec(platform)
    pf = spec.get("peak_flops") or 0.0
    return float(flops) / pf if pf > 0 else float("inf")


def mfu_metrics(
    *,
    flops: float,
    bytes_accessed: float,
    wall_s: float,
    tokens: Optional[int] = None,
    platform: Optional[str] = None,
    spec: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Join one step's cost-model totals with its measured wall time.

    Returns the journal-ready fields: ``mfu``, ``hbm_bw_util``,
    ``bound`` (``"compute"`` / ``"memory"`` / ``"balanced"``), achieved
    TFLOP/s and GB/s, arithmetic intensity vs the roofline ridge, and
    the peak-spec provenance. ``flops``/``bytes_accessed`` are per
    executed region (multiply per-step costs by the step count yourself
    when timing multi-step windows).
    """
    spec = spec or peak_spec(platform)
    out: Dict[str, Any] = {"peak_source": spec["source"]}
    if wall_s <= 0:
        return out
    ach_f = flops / wall_s
    ach_b = bytes_accessed / wall_s
    out["achieved_tflops"] = round(ach_f / 1e12, 4)
    out["achieved_hbm_gbps"] = round(ach_b / 1e9, 3)
    pf, pb = spec["peak_flops"], spec["peak_hbm_bytes_per_sec"]
    if pf:
        out["mfu"] = round(ach_f / pf, 4)
    if pb:
        out["hbm_bw_util"] = round(ach_b / pb, 4)
    if pf and pb:
        # roofline: each resource imposes a time floor; the larger floor
        # is the binding constraint for this step's cost totals
        t_compute = flops / pf
        t_memory = bytes_accessed / pb
        floor = max(t_compute, t_memory)
        if floor > 0:
            if abs(t_compute - t_memory) <= 0.1 * floor:
                out["bound"] = "balanced"
            else:
                out["bound"] = "compute" if t_compute > t_memory else "memory"
        if bytes_accessed > 0:
            out["arithmetic_intensity"] = round(flops / bytes_accessed, 2)
            out["ridge_intensity"] = round(pf / pb, 2)
    if tokens and flops:
        out["flops_per_token"] = round(flops / tokens, 1)
    return out


# ---------------------------------------------------------------------------
# step-cost extraction (the pyprof join)
# ---------------------------------------------------------------------------


def traced_step_costs(fn, *args, **kwargs) -> Dict[str, Any]:
    """FLOPs/bytes of ``fn(*args)`` from a trace only (no compile).

    Uses ``pyprof.per_scope_costs``'s jaxpr walk: FLOPs follow the
    reference handler table (GEMM shape arithmetic etc.); bytes are
    algorithmic operand+result sizes — an upper bound on HBM traffic
    (pre-fusion), so ``hbm_bw_util`` from this path overstates. Cheap
    enough to run once per prepared config when a journal is armed.
    """
    from apex_tpu.pyprof.prof import per_scope_costs

    total = per_scope_costs(fn, *args, **kwargs)["<total>"]
    return {"flops": float(total["flops"]), "bytes": float(total["bytes"]),
            "method": "jaxpr"}


def compiled_step_costs(compiled, *, jaxpr_flops: float = 0.0) -> Dict[str, Any]:
    """FLOPs/bytes off a compiled executable's XLA cost model.

    ``jaxpr_flops`` (from :func:`traced_step_costs` or
    ``pyprof._walk_flops_only``) guards the Pallas undercount: the cost
    model reports zero FLOPs inside custom-calls, so the larger of the
    two counts wins (same policy as ``pyprof.profile_fn``).
    """
    analysis = compiled.cost_analysis()
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0]
    analysis = dict(analysis)
    cm = float(analysis.get("flops", 0.0))
    flops = max(cm, float(jaxpr_flops or 0.0))
    return {
        "flops": flops,
        "bytes": float(analysis.get("bytes accessed", 0.0)),
        "method": "cost_model" if flops == cm else "cost_model+jaxpr",
    }
