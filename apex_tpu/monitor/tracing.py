"""Step-anatomy tracing: nested host-side spans + timeline analysis.

The journal (monitor/journal.py) records what a step DID per window; the
census (monitor/comms.py) counts what collectives a step CONTAINS. This
module times the ANATOMY of a step — named, nested, per-rank host-side
spans written as crash-tolerant JSON-lines (mirroring ``MetricsJournal``
semantics exactly: strict JSON, torn final lines tolerated on read) —
and turns span files into judgments:

- :func:`pipeline_anatomy`: per-rank {fwd, bwd, send, recv, bubble}
  seconds from a traced pipeline drive
  (``transformer/pipeline_parallel/schedules.traced_pipeline_timeline``)
  and the measured per-rank bubble fraction;
- :func:`expected_bubble_fraction`: the analytic floor each measured run
  is compared against — the fill/drain algebra of schedules.py's SPMD
  ring ((S-1)/(vpp*M+S-1)) and of the schedule-as-data planners
  (gpipe/1f1b/interleaved/zero-bubble; the zero-bubble engine's W/B
  split lands at (S-1)/(3M+S-1), schedules.plan_schedule);
- :func:`step_anatomy` / :func:`overlap_fraction`: measured wall time
  joined against the pyprof cost model (monitor/mfu.py peak specs) and
  collective payload bytes over the ICI bandwidth table — compute vs
  exposed-comm vs host-stall seconds whose fractions sum to 1.0 per
  window, plus the comm/compute overlap fraction (how much of the
  cheaper resource's time is hidden under the other);
- :func:`chrome_trace`: Chrome trace-event export (``chrome://tracing``
  / Perfetto) of any span file.

Timing convention (CLAUDE.md tunnel discipline): a span's clock stops on
a device→host fetch — :meth:`Span.barrier` / :func:`fetch_barrier` — of
a value whose dependency chain covers the spanned work, never a bare
``block_until_ready``. Spans are host-side only: a disarmed tracer adds
NOTHING to a step program (harness programs stay byte-identical; tests
pin this), and an armed tracer touches the device only at the barrier
fetches the caller requests.

No reference-file citation: like the rest of apex_tpu.monitor, NVIDIA
Apex has no tracing layer; the measured-bubble/overlap design follows
the MPMD pipeline (JaxPP) and eager-SPMD timeline (veScale) framings in
PAPERS.md.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Dict, IO, List, Optional, Sequence, Union

from apex_tpu.monitor.journal import (
    JournalRecords,
    MetricsJournal,
    _sanitize_nonfinite,
    _to_host,
)

ENV_TRACE = "APEX_TPU_TRACE"
ENV_PEAK_ICI_GBPS = "APEX_TPU_PEAK_ICI_GBPS"
ENV_PEAK_DCN_GBPS = "APEX_TPU_PEAK_DCN_GBPS"

#: platform substring -> aggregate per-chip ICI bytes/s (public datasheet
#: interconnect numbers, decimal GB/s; same matching rule as
#: ``mfu.PEAK_SPECS``). The cpu row exists so virtual-mesh CI produces
#: *labelled* order-of-magnitude numbers, not measurements.
ICI_SPECS = {
    "v6e": 448e9,
    "v6": 448e9,
    "v5p": 600e9,
    "v5e": 200e9,
    "v5 lite": 200e9,
    "v4": 300e9,
    "v3": 112.5e9,
    "v2": 62.5e9,
    "cpu": 10e9,
}
_ICI_FALLBACK = 300e9  # v4-class, flagged source="fallback"

#: platform substring -> per-chip DCN (inter-island / inter-host network)
#: bytes/s — the SLOW tier of a two-tier pod mesh
#: (``parallel/hierarchy.py``). Order-of-magnitude datasheet numbers
#: (per-host NICs divided across the host's chips); the point of the row
#: is the RATIO to ``ICI_SPECS`` — one to two orders of magnitude —
#: which is what makes the hierarchical decomposition and the int8 DCN
#: wire price in (EQuARX's deployment regime). Same calibration
#: precedence as the ICI row: env ``APEX_TPU_PEAK_DCN_GBPS``, outranked
#: by an armed ``APEX_TPU_CALIBRATION`` file.
DCN_SPECS = {
    "v6e": 3.125e9,   # 200 Gb/s host NIC / 8 chips
    "v6": 3.125e9,
    "v5p": 6.25e9,    # 200 Gb/s / 4 chips
    "v5e": 1.5625e9,  # 100 Gb/s / 8 chips
    "v5 lite": 1.5625e9,
    "v4": 3.125e9,    # 100 Gb/s / 4 chips
    "v3": 3.125e9,
    "v2": 1.5625e9,
    "cpu": 1e9,
}
_DCN_FALLBACK = 3.125e9  # 100 Gb/s NIC / 4 chips, flagged source="fallback"

#: schedules with known analytic bubble floors (ROADMAP item 5's menu)
SCHEDULES = ("gpipe", "1f1b", "interleaved", "zero-bubble")

#: span record fields that are NOT user attrs (chrome export keeps the rest)
_CORE_FIELDS = ("v", "kind", "ts", "name", "cat", "dur_s", "rank", "depth",
                "rank_info", "nonfinite_keys")


def _finite(v) -> bool:
    try:
        import math

        return math.isfinite(float(v))
    except Exception:  # noqa: BLE001
        return False


def fetch_barrier(value) -> None:
    """Device→host fetch of a minimal covering probe: one element per
    leading-dim entry (so every shard of a sharded array is forced),
    or the scalar itself. Never raises — a failed barrier means the
    span closes on the host clock instead of killing the run."""
    try:
        # hang-attribution breadcrumb (monitor/flight.py): a wedged
        # tunnel hangs HERE — stamp before blocking so a watchdog kill
        # report names the fetch (shape included when cheap to read)
        from apex_tpu.monitor import flight as _flight

        _flight.breadcrumb(
            f"fetch:barrier{list(getattr(value, 'shape', ()) or ())}")
    except Exception:  # noqa: BLE001 - telemetry must not kill training
        pass
    try:
        import numpy as np

        if getattr(value, "ndim", 0):
            idx = (slice(None),) + (0,) * (value.ndim - 1)
            np.asarray(value[idx])
        else:
            np.asarray(value)
    except Exception:  # noqa: BLE001 - telemetry must not kill training
        pass


class Span:
    """One open span; close via the :meth:`Tracer.span` context manager.

    ``barrier(x)`` stops the clock on a device→host fetch of ``x``
    (tunnel discipline); without it the span ends on the host clock at
    context exit. ``annotate(**attrs)`` adds fields to the record."""

    __slots__ = ("name", "cat", "attrs", "ts", "_t0", "_t1", "_tracer",
                 "depth", "barriered")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name, self.cat, self.attrs = name, cat, attrs
        self.ts = time.time()
        self._t0 = time.perf_counter()
        self._t1: Optional[float] = None
        self.depth = 0
        self.barriered = False

    def barrier(self, value) -> None:
        fetch_barrier(value)
        self._t1 = time.perf_counter()
        self.barriered = True

    def annotate(self, **attrs) -> None:
        self.attrs.update(attrs)

    @property
    def dur_s(self) -> float:
        end = self._t1 if self._t1 is not None else time.perf_counter()
        return end - self._t0


class Tracer:
    """Append-only JSON-lines span sink (``MetricsJournal`` semantics:
    strict JSON, never raises, O_APPEND-shareable, crash-tolerant read).

    >>> tracer = Tracer("out/trace.jsonl", meta={"run": "pretrain_gpt"})
    >>> with tracer.span("step", cat="host", step=3) as sp:
    ...     params, state, loss, metrics = train_step(...)
    ...     sp.barrier(loss)          # the device→host fetch stops the clock
    >>> tracer.close()

    ``path_or_file=None`` keeps records in memory only (``.records``) —
    the lint analyzers' and the traced pipeline drive's mode. ``keep=True``
    retains records in memory in addition to the file.
    """

    SCHEMA_VERSION = 1

    def __init__(
        self,
        path_or_file: Union[str, IO[str], None] = None,
        *,
        meta: Optional[Dict[str, Any]] = None,
        keep: bool = False,
        flush_every: int = 1,
    ):
        # flush_every defaults to 1 for the same reason MetricsJournal's
        # does: span files must survive a watchdog SIGKILL with
        # everything but the torn tail intact (crash-tolerance IS the
        # format's point). Raise it only for span-storms you can afford
        # to lose.
        self._f: Optional[IO[str]] = None
        self._own = False
        self.path: Optional[str] = None
        if path_or_file is None:
            keep = True
        elif hasattr(path_or_file, "write"):
            self._f = path_or_file
            self.path = getattr(path_or_file, "name", None)
        else:
            d = os.path.dirname(os.path.abspath(path_or_file))
            os.makedirs(d, exist_ok=True)
            self._f = open(path_or_file, "a")
            self._own = True
            self.path = path_or_file
        self.keep = bool(keep)
        self.records: List[Dict[str, Any]] = []
        self.flush_every = max(int(flush_every), 1)
        self._since_flush = 0
        self._stack: List[Span] = []
        self.step: Optional[int] = None  # stamped into every span record
        if meta:
            self.log(dict(meta, kind="meta"))

    # -- core sink (journal discipline: strict JSON, never raises) ----------
    def log(self, record: Dict[str, Any]) -> Dict[str, Any]:
        rec = {"v": self.SCHEMA_VERSION,
               "kind": record.get("kind", "span"),
               "ts": record.get("ts", round(time.time(), 6))}
        for k, v in record.items():
            rec[k] = _to_host(v)
        bad: List[str] = []
        rec = _sanitize_nonfinite(rec, "", bad)
        if bad:
            rec["nonfinite_keys"] = bad
        try:
            if self._f is not None:
                self._f.write(
                    json.dumps(rec, default=str, allow_nan=False) + "\n")
                self._since_flush += 1
                if self._since_flush >= self.flush_every:
                    self._f.flush()
                    self._since_flush = 0
            if self.keep:
                self.records.append(rec)
        except Exception:  # noqa: BLE001 - telemetry must not kill training
            pass
        try:
            # black-box feed: span records ride the armed flight ring
            # (monitor/flight.py) — one module-global check disarmed
            from apex_tpu.monitor import flight as _flight

            _flight.observe_record(rec)
        except Exception:  # noqa: BLE001 - telemetry must not kill training
            pass
        return rec

    # -- the span protocol --------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, *, cat: str = "host", **attrs):
        """Open a nested named span; the record lands at exit with its
        depth and measured duration. Exceptions propagate (the span still
        records, marked ``"error": true``)."""
        sp = Span(self, name, cat, dict(attrs))
        sp.depth = len(self._stack)
        self._stack.append(sp)
        try:
            yield sp
        except BaseException:
            sp.attrs.setdefault("error", True)
            raise
        finally:
            dur = sp.dur_s
            self._stack.pop()
            self._emit(sp, dur)

    def _emit(self, sp: Span, dur_s: float) -> None:
        rec: Dict[str, Any] = {"kind": "span", "ts": round(sp.ts, 6),
                               "name": sp.name, "cat": sp.cat,
                               "dur_s": dur_s, "depth": sp.depth}
        if self.step is not None and "step" not in sp.attrs:
            rec["step"] = self.step
        rec.update(sp.attrs)
        rec.setdefault("rank", 0)
        self.log(rec)

    def record(self, name: str, *, dur_s: float, cat: str = "host",
               rank: int = 0, ts: Optional[float] = None,
               depth: int = 0, **attrs) -> Dict[str, Any]:
        """Post-hoc span emission for measured intervals — the traced
        pipeline drive's per-rank attribution path (one measured tick
        interval lands as one span PER RANK, live/idle decoded from the
        schedule algebra)."""
        if ts is None:
            # back-date by the duration when it is usable; a non-finite
            # duration must not poison the timestamp too
            ts = time.time() - (dur_s if _finite(dur_s) else 0.0)
        rec: Dict[str, Any] = {"kind": "span", "ts": round(ts, 6),
                               "name": name, "cat": cat, "dur_s": dur_s,
                               "rank": int(rank), "depth": int(depth)}
        if self.step is not None and "step" not in attrs:
            rec["step"] = self.step
        rec.update(attrs)
        return self.log(rec)

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        try:
            if self._f is not None:
                self._f.flush()
                if self._own:
                    self._f.close()
        except Exception:  # noqa: BLE001
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    #: crash-tolerant JSON-lines read (shared with the journal: same
    #: truncated/bad_lines semantics — tests pin the mirror)
    read = staticmethod(MetricsJournal.read)


# ---------------------------------------------------------------------------
# global arming (the harness opt-in: --trace / BENCH_TRACE / APEX_TPU_TRACE)
# ---------------------------------------------------------------------------

_GLOBAL: Optional[Tracer] = None
_ENV_CHECKED = False


def arm(path_or_file: Union[str, IO[str], None] = None, *,
        meta: Optional[Dict[str, Any]] = None, keep: bool = False) -> Tracer:
    """Install the process-global tracer (replacing any previous one)."""
    global _GLOBAL
    if _GLOBAL is not None:
        _GLOBAL.close()
    _GLOBAL = Tracer(path_or_file, meta=meta, keep=keep)
    return _GLOBAL


def disarm() -> None:
    global _GLOBAL, _ENV_CHECKED
    if _GLOBAL is not None:
        _GLOBAL.close()
    _GLOBAL = None
    _ENV_CHECKED = True  # an explicit disarm also wins over the env


def get_tracer() -> Optional[Tracer]:
    """The armed tracer, or None. ``APEX_TPU_TRACE=<path>`` arms lazily on
    first lookup, so any harness that consults the tracer inherits the
    env opt-in without wiring."""
    global _GLOBAL, _ENV_CHECKED
    if _GLOBAL is None and not _ENV_CHECKED:
        _ENV_CHECKED = True
        path = os.environ.get(ENV_TRACE)
        if path:
            try:
                _GLOBAL = Tracer(path)
            except Exception:  # noqa: BLE001 - telemetry must not kill a run
                _GLOBAL = None
    return _GLOBAL


def armed() -> bool:
    return get_tracer() is not None


@contextlib.contextmanager
def scoped(tracer: Optional[Tracer]):
    """Temporarily install ``tracer`` as the global (lint analyzers and
    tests; restores the previous arming on exit)."""
    global _GLOBAL, _ENV_CHECKED
    prev, prev_checked = _GLOBAL, _ENV_CHECKED
    _GLOBAL, _ENV_CHECKED = tracer, True
    try:
        yield tracer
    finally:
        _GLOBAL, _ENV_CHECKED = prev, prev_checked


@contextlib.contextmanager
def maybe_span(tracer: Optional[Tracer], name: str, *, cat: str = "host",
               **attrs):
    """``tracer.span(...)`` when armed, a no-op Span otherwise — so hot
    loops wire one context manager and pay nothing disarmed."""
    if tracer is None:
        yield _NULL_SPAN
    else:
        with tracer.span(name, cat=cat, **attrs) as sp:
            yield sp


class _NullSpan:
    __slots__ = ()

    def barrier(self, value) -> None:  # noqa: D401 - protocol stub
        pass

    def annotate(self, **attrs) -> None:
        pass


_NULL_SPAN = _NullSpan()


# ---------------------------------------------------------------------------
# analytic schedule simulator
# ---------------------------------------------------------------------------


def expected_bubble_fraction(schedule: str, num_microbatches: int,
                             stages: int,
                             virtual_pipeline_size: int = 1) -> float:
    """Analytic bubble floor of a pipeline schedule — the fraction of a
    rank's slot timeline spent idle in fill/drain, assuming uniform slot
    durations (the classical (S-1)/(ticks) algebra; Megatron/JaxPP's
    bubble accounting):

    - ``"gpipe"`` / ``"1f1b"``: ``(S-1)/(M+S-1)`` — 1F1B reorders the
      steady state (bounding activation memory) but fills/drains the
      same S-1 slots;
    - ``"interleaved"``: ``(S-1)/(vpp*M+S-1)`` — the vpp-chunk placement
      of schedules.py's SPMD ring (``pipeline_tick_count``); vpp=1
      degenerates to 1F1B;
    - ``"zero-bubble"``: ``(S-1)/(3M+S-1)`` — the W/B split
      (``schedules.plan_schedule``) factors each backward slot into an
      input-grad and a weight-grad slot, so a rank's timeline is ``3M``
      live slots and the ``bwd_weight`` slots of early microbatches fill
      what 1F1B spends idle in the cooldown: per-rank idles drop from
      ``2(S-1)`` (out of ``2(M+S-1)`` ticks) to the ``S-1`` fill ticks no
      schedule can remove (rank s has no input before tick s). The greedy
      planner meets this floor exactly (tests pin plan-counted ==
      closed-form).

    Measured runs (:func:`pipeline_anatomy`) are compared against this
    floor; ``report compare --bubble-threshold`` gates regressions.
    """
    M, S, v = int(num_microbatches), int(stages), int(virtual_pipeline_size)
    if M <= 0 or S <= 0 or v <= 0:
        raise ValueError(f"need positive M/S/vpp, got {M}/{S}/{v}")
    if S == 1:
        return 0.0
    name = schedule.lower().replace("_", "-")
    if name in ("gpipe", "1f1b"):
        return (S - 1) / (M + S - 1)
    if name in ("interleaved", "1f1b-interleaved", "vpp"):
        return (S - 1) / (v * M + S - 1)
    if name in ("zero-bubble", "zb", "zerobubble"):
        return (S - 1) / (3 * M + S - 1)
    raise ValueError(f"unknown schedule {schedule!r}; known: {SCHEDULES}")


# ---------------------------------------------------------------------------
# measured anatomy: wall time vs cost-model compute and wire-model comm
# ---------------------------------------------------------------------------


def ici_spec(platform: Optional[str] = None) -> Dict[str, Any]:
    """Resolve ``{platform, ici_bytes_per_sec, source}`` — the wire-speed
    denominator for modeled comm seconds. ``APEX_TPU_PEAK_ICI_GBPS``
    (decimal GB/s) overrides, mirroring ``mfu.peak_spec``'s calibration
    knobs; otherwise the datasheet table row; otherwise the flagged
    v4-class fallback."""
    from apex_tpu.monitor import mfu as _mfu

    plat = (platform or _mfu._detect_platform()).lower()
    bw, source = None, None
    for key, b in ICI_SPECS.items():
        if key in plat:
            bw, source = b, f"table:{key}"
            break
    if bw is None:
        bw, source = _ICI_FALLBACK, "fallback"
    try:
        env = os.environ.get(ENV_PEAK_ICI_GBPS)
        if env:
            bw, source = float(env) * 1e9, "env"
    except ValueError:
        pass  # malformed override: keep the table row
    # an armed calibration file (APEX_TPU_CALIBRATION) outranks the env
    # knob — same precedence as mfu.peak_spec; disarmed: unchanged
    try:
        from apex_tpu.monitor import calibrate as _calibrate

        cal = _calibrate.active()
    except Exception:  # noqa: BLE001 - calibration is best-effort
        cal = None
    if cal:
        ci = cal.get("peak_ici_bytes_per_sec")
        if isinstance(ci, (int, float)) and ci > 0:
            bw, source = float(ci), "calibrated"
    return {"platform": plat, "ici_bytes_per_sec": bw, "source": source}


def dcn_spec(platform: Optional[str] = None) -> Dict[str, Any]:
    """Resolve ``{platform, dcn_bytes_per_sec, source}`` — the slow-tier
    wire-speed denominator for inter-island (DCN) comm seconds on a
    two-tier pod mesh. Mirror of :func:`ici_spec` with its own table
    (``DCN_SPECS``), env knob (``APEX_TPU_PEAK_DCN_GBPS``, decimal GB/s)
    and calibration key (``peak_dcn_bytes_per_sec``) — an armed
    ``APEX_TPU_CALIBRATION`` file outranks the env, same precedence."""
    from apex_tpu.monitor import mfu as _mfu

    plat = (platform or _mfu._detect_platform()).lower()
    bw, source = None, None
    for key, b in DCN_SPECS.items():
        if key in plat:
            bw, source = b, f"table:{key}"
            break
    if bw is None:
        bw, source = _DCN_FALLBACK, "fallback"
    try:
        env = os.environ.get(ENV_PEAK_DCN_GBPS)
        if env:
            bw, source = float(env) * 1e9, "env"
    except ValueError:
        pass  # malformed override: keep the table row
    try:
        from apex_tpu.monitor import calibrate as _calibrate

        cal = _calibrate.active()
    except Exception:  # noqa: BLE001 - calibration is best-effort
        cal = None
    if cal:
        cd = cal.get("peak_dcn_bytes_per_sec")
        if isinstance(cd, (int, float)) and cd > 0:
            bw, source = float(cd), "calibrated"
    return {"platform": plat, "dcn_bytes_per_sec": bw, "source": source}


def modeled_step_seconds(
    *,
    flops: float,
    comm_bytes: float,
    bubble_fraction: float = 0.0,
    hidden_comm_bytes: float = 0.0,
    overhead_s: float = 0.0,
    dcn_bytes: float = 0.0,
    spec: Optional[Dict[str, Any]] = None,
    ici: Optional[Dict[str, Any]] = None,
    dcn: Optional[Dict[str, Any]] = None,
    platform: Optional[str] = None,
) -> Dict[str, Any]:
    """Compose one modeled step time from the analytic legs — the
    planner's (``apex_tpu.plan``) scoring closure.

    ``flops / peak_flops`` (``mfu.modeled_compute_seconds``) inflated by
    the schedule's bubble floor, plus the exposed wire time:
    ``comm_bytes / ici_bytes_per_sec`` minus whatever
    ``hidden_comm_bytes`` overlap (e.g. the ZeRO-3 prefetched gathers)
    can hide under compute — capped at the compute time itself, the same
    cap :func:`step_anatomy` applies to measured overlap. Both
    denominators resolve through :func:`mfu.peak_spec` /
    :func:`ici_spec`, so an armed ``APEX_TPU_CALIBRATION`` file (ISSUE
    16) calibrates every planner prediction with no extra wiring.
    Returns the decomposition, never just the total, so consumers can
    stamp ``compute_s``/``exposed_comm_s`` provenance.

    ``dcn_bytes`` prices the SLOW tier of a two-tier pod mesh
    (``parallel/hierarchy.py``): that payload divides by
    :func:`dcn_spec`'s bandwidth instead and lands as its own
    always-exposed leg (``dcn_comm_s`` — the inter-island exchange is
    one blocking hop, outside the overlap budget). ``comm_bytes`` stays
    the ICI-tier payload; per-tier keys appear only when a DCN payload
    is priced, so single-tier consumers are byte-identical.
    """
    from apex_tpu.monitor import mfu as _mfu

    spec = spec or _mfu.peak_spec(platform)
    ici = ici or ici_spec(platform)
    compute_s = _mfu.modeled_compute_seconds(flops, spec=spec)
    bw = ici.get("ici_bytes_per_sec") or 0.0
    comm_s = float(comm_bytes) / bw if bw > 0 else 0.0
    hidden_s = min(float(hidden_comm_bytes) / bw, compute_s) if bw > 0 else 0.0
    exposed_s = max(comm_s - hidden_s, 0.0)
    dcn_s = 0.0
    if dcn_bytes:
        dcn = dcn or dcn_spec(platform)
        dbw = dcn.get("dcn_bytes_per_sec") or 0.0
        dcn_s = float(dcn_bytes) / dbw if dbw > 0 else 0.0
    bub = min(max(float(bubble_fraction), 0.0), 0.99)
    step_s = compute_s / (1.0 - bub) + exposed_s + dcn_s + float(overhead_s)
    out = {
        "step_seconds": step_s,
        "compute_s": compute_s,
        "comm_s": comm_s,
        "exposed_comm_s": exposed_s,
        "hidden_comm_s": hidden_s,
        "bubble_fraction": bub,
        "overhead_s": float(overhead_s),
        "peak_source": spec.get("source"),
        "ici_source": ici.get("source"),
    }
    if dcn_bytes:
        out["dcn_comm_s"] = dcn_s
        out["dcn_source"] = dcn.get("source")
    return out


def overlap_fraction(wall_s: float, compute_s: float,
                     comm_s: float) -> Optional[float]:
    """Measured comm/compute overlap: of the cheaper resource's seconds,
    the fraction hidden under the other. ``compute_s + comm_s - wall_s``
    is the overlapped time (0 when the phases serialized; the full
    ``min`` when one hides entirely under the other). None when either
    component is zero (nothing to overlap)."""
    lo = min(compute_s, comm_s)
    if lo <= 0 or wall_s <= 0:
        return None
    ov = max(0.0, min(compute_s + comm_s - wall_s, lo))
    return round(ov / lo, 4)


def step_anatomy(
    *,
    wall_s: float,
    compute_s: Optional[float] = None,
    comm_s: Optional[float] = None,
    flops: Optional[float] = None,
    comm_bytes: Optional[float] = None,
    dcn_s: Optional[float] = None,
    dcn_bytes: Optional[float] = None,
    spec: Optional[Dict[str, Any]] = None,
    ici: Optional[Dict[str, Any]] = None,
    dcn: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Decompose one measured step/window into compute vs exposed-comm vs
    host-stall seconds.

    ``compute_s`` defaults to ``flops / peak_flops`` (``mfu.peak_spec``)
    and ``comm_s`` to ``comm_bytes / ici_bytes_per_sec``
    (:func:`ici_spec`) — the pyprof-cost-model/census join; pass measured
    seconds (e.g. phase spans from a traced ZeRO step) to bypass the
    models. Components clip to the measured wall, so
    ``compute_frac + comm_frac + stall_frac == 1.0`` per window by
    construction (tests pin the invariant), and ``overlap_fraction``
    reports how much of the cheaper component hid under the other.

    On a two-tier pod mesh, ``dcn_s`` (measured) or ``dcn_bytes``
    (modeled via :func:`dcn_spec`) adds the slow-tier leg: total comm
    becomes ICI + DCN, and the output gains ``ici_s``/``dcn_s`` — the
    per-LINK-CLASS comm seconds — with the exposed time split
    pro-rata, so ``report`` can attribute exposed comm per tier. The
    fraction invariant is unchanged. Single-tier calls (no dcn args)
    are byte-identical to before."""
    out: Dict[str, Any] = {"wall_s": round(wall_s, 6)}
    if wall_s <= 0:
        return out
    if compute_s is None and flops is not None:
        from apex_tpu.monitor import mfu as _mfu

        spec = spec or _mfu.peak_spec()
        compute_s = float(flops) / float(spec["peak_flops"])
        out["compute_source"] = f"cost_model/{spec['source']}"
    if comm_s is None and comm_bytes is not None:
        ici = ici or ici_spec()
        comm_s = float(comm_bytes) / float(ici["ici_bytes_per_sec"])
        out["comm_source"] = f"wire_model/{ici['source']}"
    if dcn_s is None and dcn_bytes is not None:
        dcn = dcn or dcn_spec()
        dcn_s = float(dcn_bytes) / float(dcn["dcn_bytes_per_sec"])
        out["dcn_source"] = f"wire_model/{dcn['source']}"
    tiered = dcn_s is not None
    ici_part = max(float(comm_s or 0.0), 0.0)
    if tiered:
        comm_s = ici_part + max(float(dcn_s), 0.0)
    compute_s = min(max(float(compute_s or 0.0), 0.0), wall_s)
    comm_s = min(max(float(comm_s or 0.0), 0.0), wall_s)
    lo = min(compute_s, comm_s)
    overlap_s = max(0.0, min(compute_s + comm_s - wall_s, lo))
    exposed_comm_s = comm_s - overlap_s
    stall_s = max(0.0, wall_s - compute_s - exposed_comm_s)
    out.update({
        "compute_s": round(compute_s, 6),
        "comm_s": round(comm_s, 6),
        "exposed_comm_s": round(exposed_comm_s, 6),
        "host_stall_s": round(stall_s, 6),
        "compute_frac": round(compute_s / wall_s, 4),
        "comm_frac": round(exposed_comm_s / wall_s, 4),
        "stall_frac": round(stall_s / wall_s, 4),
    })
    if tiered:
        # per-link-class attribution: the exposed seconds split in the
        # tiers' modeled proportions (both tiers clip together above)
        share = max(float(dcn_s), 0.0) / max(ici_part + float(dcn_s), 1e-30)
        out["dcn_s"] = round(exposed_comm_s * share, 6)
        out["ici_s"] = round(exposed_comm_s * (1.0 - share), 6)
    ov = overlap_fraction(wall_s, compute_s, comm_s)
    if ov is not None:
        out["overlap_fraction"] = ov
    return out


# ---------------------------------------------------------------------------
# span-file analyzers
# ---------------------------------------------------------------------------


def _spans(records: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [r for r in records
            if r.get("kind") == "span"
            and isinstance(r.get("dur_s"), (int, float))]


def pipeline_anatomy(records: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Join a traced pipeline drive's spans into the per-rank slot
    anatomy: {fwd, bwd, send, recv, bubble} seconds per rank, the
    measured bubble fraction ``bubble / (fwd + bwd + bubble)`` (the
    compute-slot timeline — comm rides its own track), and per-microbatch
    slot totals. Spans come from
    ``schedules.traced_pipeline_timeline`` (cat ``"pipe"`` slots, cat
    ``"pipe-comm"`` send/recv)."""
    ranks: Dict[int, Dict[str, float]] = {}
    micro: Dict[int, Dict[str, float]] = {}
    for r in _spans(records):
        cat = r.get("cat")
        if cat not in ("pipe", "pipe-comm"):
            continue
        rk = int(r.get("rank") or 0)
        row = ranks.setdefault(rk, {"fwd_s": 0.0, "bwd_s": 0.0,
                                    "bubble_s": 0.0, "send_s": 0.0,
                                    "recv_s": 0.0})
        name = r.get("name", "")
        key = f"{name}_s"
        if key in row:
            row[key] += r["dur_s"]
        m = r.get("microbatch")
        if m is not None and name in ("fwd", "bwd", "send", "recv"):
            mrow = micro.setdefault(int(m), {"fwd_s": 0.0, "bwd_s": 0.0,
                                             "send_s": 0.0, "recv_s": 0.0})
            mrow[key] += r["dur_s"]
    per_rank = {}
    fracs = []
    for rk, row in sorted(ranks.items()):
        slot_total = row["fwd_s"] + row["bwd_s"] + row["bubble_s"]
        frac = row["bubble_s"] / slot_total if slot_total > 0 else 0.0
        fracs.append(frac)
        per_rank[str(rk)] = dict(
            {k: round(v, 6) for k, v in row.items()},
            bubble_fraction=round(frac, 4))
    out: Dict[str, Any] = {"ranks": per_rank}
    if fracs:
        out["bubble_fraction"] = {
            "mean": round(sum(fracs) / len(fracs), 4),
            "max": round(max(fracs), 4),
            "min": round(min(fracs), 4),
        }
    if micro:
        out["microbatches"] = {
            str(m): {k: round(v, 6) for k, v in row.items()}
            for m, row in sorted(micro.items())}
    return out


def timeline_summary(records: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Roll a span file up: per-category seconds, per-step phase anatomy
    (spans sharing a ``step`` attr), and the pipeline anatomy when pipe
    spans are present — the ``monitor.report`` timeline section's
    input."""
    spans = _spans(records)
    by_cat: Dict[str, Dict[str, float]] = {}
    for r in spans:
        row = by_cat.setdefault(r.get("cat", "host"),
                                {"seconds": 0.0, "count": 0})
        row["seconds"] += r["dur_s"]
        row["count"] += 1
    out: Dict[str, Any] = {
        "spans": len(spans),
        "by_cat": {c: {"seconds": round(v["seconds"], 6),
                       "count": int(v["count"])}
                   for c, v in sorted(by_cat.items())},
    }
    # per-step phase anatomy: a "step" span is the wall; inner compute/
    # comm-cat spans at depth>0 are its phases (the traced ZeRO step's
    # grads/apply split) — phases serialize host-side, so overlap here is
    # structural 0 and the interesting numbers are the phase shares
    steps: Dict[Any, Dict[str, float]] = {}
    for r in spans:
        st = r.get("step")
        if st is None:
            continue
        row = steps.setdefault(st, {"wall_s": 0.0, "compute_s": 0.0,
                                    "comm_s": 0.0})
        if r.get("name") == "step":
            row["wall_s"] += r["dur_s"]
        elif r.get("cat") == "compute":
            row["compute_s"] += r["dur_s"]
        elif r.get("cat") == "comm":
            row["comm_s"] += r["dur_s"]
    phased = [v for v in steps.values()
              if v["wall_s"] > 0 and (v["compute_s"] or v["comm_s"])]
    if phased:
        n = len(phased)
        out["steps"] = {
            "count": n,
            "wall_s_mean": round(sum(v["wall_s"] for v in phased) / n, 6),
            "compute_frac_mean": round(
                sum(min(v["compute_s"] / v["wall_s"], 1.0)
                    for v in phased) / n, 4),
            "comm_frac_mean": round(
                sum(min(v["comm_s"] / v["wall_s"], 1.0)
                    for v in phased) / n, 4),
        }
    if any(r.get("cat") in ("pipe", "pipe-comm") for r in spans):
        out["pipeline"] = pipeline_anatomy(records)
    return out


# ---------------------------------------------------------------------------
# Chrome trace-event export (chrome://tracing / Perfetto)
# ---------------------------------------------------------------------------

#: category -> thread id within a rank's process row (compute track 0,
#: comm track 1, host track 2)
_TRACKS = {"pipe": 0, "compute": 0, "pipe-comm": 1, "comm": 1}


def chrome_trace(records: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Convert span records to the Chrome trace-event JSON format:
    complete (``"ph": "X"``) events, one process row per rank, compute/
    comm/host thread tracks — plus one lane per sampled serving request
    (spans carrying a ``request`` attr share a named thread). The dict
    round-trips ``json.dumps`` → ``chrome://tracing`` / Perfetto load."""
    spans = _spans(records)
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(r.get("ts", 0.0) for r in spans)
    events: List[Dict[str, Any]] = []
    pids = set()
    req_lanes: Dict[str, int] = {}
    req_lane_pid: Dict[str, int] = {}
    for r in spans:
        pid = int(r.get("rank") or 0)
        pids.add(pid)
        cat = r.get("cat", "host")
        req = r.get("request")
        if req is not None:
            # request-scoped spans get a dedicated lane (tids >= 16 keep
            # clear of the compute/comm/host depth tracks)
            key = str(req)
            tid = req_lanes.setdefault(key, 16 + len(req_lanes))
            req_lane_pid.setdefault(key, pid)
        else:
            tid = _TRACKS.get(cat, 2 + int(r.get("depth") or 0))
        args = {k: v for k, v in r.items()
                if k not in _CORE_FIELDS and v is not None}
        events.append({
            "ph": "X", "name": str(r.get("name", "?")), "cat": cat,
            "pid": pid, "tid": tid,
            "ts": round((r.get("ts", t0) - t0) * 1e6, 3),
            "dur": round(max(float(r["dur_s"]), 0.0) * 1e6, 3),
            "args": args,
        })
    for pid in sorted(pids):
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": f"rank {pid}"}})
    for key, tid in req_lanes.items():
        events.append({"ph": "M", "name": "thread_name",
                       "pid": req_lane_pid[key], "tid": tid,
                       "args": {"name": f"request {key}"}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(trace_path: str, out_path: str) -> Dict[str, Any]:
    """Read a span JSON-lines file and write the Chrome trace next to it;
    returns the trace dict. Crash-truncated span files export their good
    prefix (``Tracer.read`` tolerance)."""
    trace = chrome_trace(Tracer.read(trace_path))
    d = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(d, exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(trace, f)
    return trace


__all__ = [
    "Tracer", "Span", "JournalRecords",
    "arm", "disarm", "get_tracer", "armed", "scoped", "maybe_span",
    "fetch_barrier",
    "expected_bubble_fraction", "SCHEDULES",
    "ici_spec", "dcn_spec", "overlap_fraction", "step_anatomy",
    "pipeline_anatomy", "timeline_summary",
    "chrome_trace", "write_chrome_trace",
    "ENV_TRACE", "ENV_PEAK_ICI_GBPS", "ICI_SPECS",
    "ENV_PEAK_DCN_GBPS", "DCN_SPECS",
]
