"""``python -m apex_tpu.monitor.selftest`` — fast off-TPU telemetry smoke.

Proves, in seconds and on any backend (forced to CPU when run as a module),
that the four monitor pieces stay importable and functional:

1. journal: step records round-trip through JSON-lines with the required
   schema fields (wall time, tokens/s, loss, loss-scale state, grad norm,
   overflow counter, rank info, HBM sample);
2. watchdog: a healthy child passes through; a deliberately-hung child is
   killed at the deadline and its last checkpoint is recovered;
3. hbm: a toy loop that retains arrays shows monotone visible growth, a
   non-retaining loop stays flat;
4. comms: traced collectives land in a :class:`CommAccount` keyed by axis.

Wired into ``__graft_entry__.dryrun_multichip`` so the multi-chip gate also
proves telemetry stays cheap. Prints one JSON line; exit 0 iff ``all_ok``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def _check_journal() -> dict:
    import jax.numpy as jnp

    from apex_tpu.monitor.journal import MetricsJournal

    fd, path = tempfile.mkstemp(prefix="apex_tpu_journal_", suffix=".jsonl")
    os.close(fd)
    try:
        with MetricsJournal(path, meta={"run": "selftest"},
                            sample_hbm_every=1) as j:
            for step in range(3):
                j.step_start()
                loss = jnp.asarray(2.5 - 0.1 * step, jnp.float32)
                metrics = {"found_inf": jnp.asarray(step == 1),
                           "loss_scale": jnp.asarray(2.0 ** 16, jnp.float32),
                           "grad_norm": jnp.asarray(1.25, jnp.float32)}
                j.step_end(step=step, loss=loss, tokens=4096, metrics=metrics)
        rows = MetricsJournal.read(path)
        steps = [r for r in rows if r["kind"] == "step"]
        assert rows[0]["kind"] == "meta" and rows[0]["run"] == "selftest"
        assert len(steps) == 3, rows
        for field in ("wall_s", "loss", "tokens_per_sec", "loss_scale",
                      "grad_norm", "overflows", "rank", "rank_info", "hbm"):
            assert field in steps[-1], (field, steps[-1])
        assert steps[-1]["overflows"] == 1  # the step-1 found_inf counted
        assert steps[-1]["hbm"]["count"] >= 0
        return {"ok": True, "records": len(rows)}
    finally:
        os.unlink(path)


def _check_watchdog() -> dict:
    from apex_tpu.monitor.watchdog import run_under_watchdog

    # -S skips sitecustomize (which can import an accelerator plugin and
    # take seconds) so the stub children start fast — bench.py test idiom
    healthy = run_under_watchdog(
        [sys.executable, "-S", "-c", "print('alive')"], deadline=30)
    assert healthy.status == "ok" and healthy.returncode == 0, healthy
    assert "alive" in healthy.stdout

    hang = (
        "import json, os, time\n"
        "with open(os.environ['APEX_TPU_CHECKPOINT_PATH'], 'w') as f:\n"
        "    json.dump({'stage': 'two', 'value': 7}, f)\n"
        "time.sleep(60)\n"
    )
    hung = run_under_watchdog([sys.executable, "-S", "-c", hang],
                              deadline=2, poll_s=0.1)
    assert hung.status == "deadline", hung
    assert hung.record == {"stage": "two", "value": 7}, hung.record
    return {"ok": True, "hung_child_recovered_stage": hung.record["stage"]}


def _check_hbm() -> dict:
    import jax.numpy as jnp

    from apex_tpu.monitor.hbm import HBMMonitor, lane_padded_bytes

    # the T(8,128) layout tax: a (512, 1) f32 column pads 128x in lanes
    assert lane_padded_bytes((512, 1), 4) == 512 * 128 * 4

    leak = HBMMonitor()
    leak.sample("baseline")
    retained = []
    for i in range(4):
        retained.append(jnp.ones((256, 256), jnp.float32) * i)
        leak.sample(f"iter{i}")
    growth = leak.growth_bytes()
    assert growth >= 4 * 256 * 256 * 4, growth

    flat = HBMMonitor()
    flat.sample("baseline")
    for i in range(4):
        _ = float(jnp.sum(jnp.ones((256, 256), jnp.float32)))
        flat.sample(f"iter{i}")
    assert abs(flat.growth_bytes()) < 256 * 256 * 4, flat.samples
    del retained
    return {"ok": True, "leak_growth_bytes": growth}


def _check_comms() -> dict:
    import jax
    import jax.numpy as jnp

    from apex_tpu.monitor.comms import comm_accounting
    from apex_tpu.parallel import collectives

    def fn(x):
        y = collectives.psum(x, "i")
        return collectives.pmean(y, "i")

    x = jnp.ones((2, 8, 16), jnp.float32)
    with comm_accounting() as acct:
        # vmap binds the axis name without needing a mesh — trace only
        jax.make_jaxpr(jax.vmap(fn, axis_name="i"))(x)
    per_axis = acct.by_axis()
    expect = 8 * 16 * 4  # per-shard payload of each collective call site
    assert per_axis["i"]["calls"] == 2, per_axis
    assert per_axis["i"]["bytes"] == 2 * expect, per_axis
    return {"ok": True, "by_axis": per_axis}


def run() -> dict:
    """In-process smoke (no platform mutation — safe under any backend)."""
    results = {}
    for name, fn in (("journal", _check_journal),
                     ("watchdog", _check_watchdog),
                     ("hbm", _check_hbm),
                     ("comms", _check_comms)):
        try:
            results[name] = fn()
        except Exception as e:  # noqa: BLE001 - report, don't crash the gate
            results[name] = {"ok": False, "error": f"{type(e).__name__}: "
                                                   f"{str(e)[:300]}"}
    results["all_ok"] = all(v.get("ok") for v in results.values()
                            if isinstance(v, dict))
    return results


def main() -> int:
    # standalone runs must stay off any ambient accelerator plugin (the
    # axon tunnel ignores JAX_PLATFORMS env; force in code, CLAUDE.md)
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - backend already up: run on it
        pass
    results = run()
    print(json.dumps({"monitor_selftest": results}))
    return 0 if results["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
