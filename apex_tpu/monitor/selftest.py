"""``python -m apex_tpu.monitor.selftest`` — fast off-TPU telemetry smoke.

Proves, in seconds and on any backend (forced to CPU when run as a module),
that the monitor pieces stay importable and functional:

1. journal: step records round-trip through JSON-lines with the required
   schema fields (wall time, tokens/s, loss, loss-scale state, grad norm,
   overflow counter, rank info, HBM sample); non-finite values sanitize
   to strict JSON; a truncated final line still parses;
1b. flight (ISSUE 14): journal records + breadcrumbs ring in the armed
   flight recorder and an explicit dump round-trips as strict JSON with
   the HBM snapshot and loss-scale state; a corrupt dump loads as None;

1c. health (ISSUE 14): the online rule monitor fires exactly the
   loss-spike rule on a seeded spike (journal wiring and the offline
   ``health.scan`` agree), a clean journal fires none, and a seeded SLO
   window under its target fires slo-burn;

2. watchdog: a healthy child passes through; a deliberately-hung child is
   killed at the deadline and its last checkpoint is recovered (the kill
   report carrying the structured heartbeat's stage attribution);
3. hbm: a toy loop that retains arrays shows monotone visible growth, a
   non-retaining loop stays flat;
4. comms: traced collectives land in a :class:`CommAccount` keyed by axis;
5. mfu: the peak-spec table resolves and the roofline join produces
   ``mfu``/``hbm_bw_util``/``bound`` for a known cost/wall pair;
6. diagnose: a forced overflow emits a forensic record naming the
   non-finite parameter group; the recompile tracker counts a cache miss
   per fresh argument shape;
7. report: the analysis CLI summarizes a journal and the compare gate
   exits non-zero exactly on regression;
7b. ledger (ISSUE 16): run-ledger appends round-trip through the
   crash-tolerant reader (a torn final line still parses), trend groups
   by config fingerprint, the N-run regress gate passes its own history
   and exits non-zero on a seeded throughput drop, and a fitted
   calibration file round-trips — armed via ``APEX_TPU_CALIBRATION`` it
   outranks the ``APEX_TPU_PEAK_*`` env overrides in ``mfu.peak_spec``;
8. lint: the source-invariant linter (``apex_tpu.lint``) reports the tree
   clean (all suppressions justified) and the trace analyzers reproduce
   the known hazards — the d=32/(sq,1) lane-padding numbers, the bare
   ``pmean(loss)``-under-grad transpose, python-scalar signature leaks,
   and the ZeRO double-reduction tripwire (a bulk data-axis grad psum
   alongside a sharded optimizer; the decomposed scatter/gather passes),
   plus the ZeRO-3 bulk-gather tripwire (a model-sized param all_gather
   in a fully-sharded step; per-layer JIT gathers pass), plus the
   quantized-collective tripwire (a surviving fp32 bulk reduce payload in
   a step that requests a quantized grad reduce, and a quantized grad
   reduce with no error-feedback residual leaf; the encoded all_to_all
   pair with a residual passes), plus the gather-prefetch tripwire
   (per-layer ZeRO-3 gathers fused inside rematerialized bodies flag;
   the double-buffered free-standing gathers pass).

8b. audit: the whole-program step-audit gate (``apex_tpu.lint.audit``,
   ISSUE 13) runs every registered IR pass + tripwire over the small
   dense and zero canonical train steps on the shared single-trace
   walker and the verdict is clean — same contract as
   ``python -m apex_tpu.lint.audit`` over the full program set;

8c. pod (ISSUE 19): the two-tier wire layer — ``tracing.dcn_spec``
   resolves the modeled DCN row (env-overridable), ``step_anatomy``
   splits exposed comm into ``ici_s``/``dcn_s`` without moving the
   fraction invariant, the ``flat-dcn-collective`` trace analyzer flags
   a bulk collective binding the DCN axis jointly with another axis
   while the hierarchical single-axis stages (``parallel/hierarchy.py``)
   and scalar loss/overflow collectives pass, and the ``pod`` canonical
   audit program (the hierarchical ZeRO apply with the int8 DCN wire)
   audits clean;

9. tracing: nested spans round-trip with depths and strict-JSON
   non-finite handling; a torn trace file still parses; the analytic
   bubble floors and the step-anatomy fraction invariant (compute +
   exposed-comm + stall == 1.0) hold at hand-computable points; a
   synthetic 2-rank slot timeline measures the bubble the algebra
   predicts; Chrome trace export round-trips ``json``; and the
   untimed-schedule tripwire flags a pipeline drive that emits no spans
   under an armed tracer (a span-emitting drive passes).

10. serve: the inference engine (apex_tpu.serve) greedily decodes two
    continuous-batched requests through the paged KV cache and the
    tokens match the full-context forward's argmax at every position;
    pages and slots all release; per-request journal records roll up
    into report's serving section; the decode-recompile tripwire
    passes the engine's real tick argument stream while flagging a
    growing per-request KV tensor; a SHARED-PREFIX pair through a
    prefix-cache + speculative engine has the second request skip
    prefill to its divergence point with zero page leaks after the
    cache drops; and the extended tripwire audits the chunked-prefill
    and speculative-verify streams both ways (clean real streams pass,
    a growing chunk width / python-int draft length is flagged by
    stream name).

Wired into ``__graft_entry__.dryrun_multichip`` so the multi-chip gate also
proves telemetry stays cheap. Prints one JSON line; exit 0 iff ``all_ok``.

No reference-file citation: like the rest of apex_tpu.monitor, the
reference has no telemetry layer (monitor/__init__.py).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def _check_journal() -> dict:
    import jax.numpy as jnp

    from apex_tpu.monitor.journal import MetricsJournal

    fd, path = tempfile.mkstemp(prefix="apex_tpu_journal_", suffix=".jsonl")
    os.close(fd)
    try:
        with MetricsJournal(path, meta={"run": "selftest"},
                            sample_hbm_every=1) as j:
            for step in range(3):
                j.step_start()
                loss = jnp.asarray(2.5 - 0.1 * step, jnp.float32)
                metrics = {"found_inf": jnp.asarray(step == 1),
                           "loss_scale": jnp.asarray(2.0 ** 16, jnp.float32),
                           "grad_norm": jnp.asarray(1.25, jnp.float32)}
                j.step_end(step=step, loss=loss, tokens=4096, metrics=metrics)
        rows = MetricsJournal.read(path)
        steps = [r for r in rows if r["kind"] == "step"]
        assert rows[0]["kind"] == "meta" and rows[0]["run"] == "selftest"
        assert len(steps) == 3, rows
        for field in ("wall_s", "loss", "tokens_per_sec", "loss_scale",
                      "grad_norm", "overflows", "rank", "rank_info", "hbm"):
            assert field in steps[-1], (field, steps[-1])
        assert steps[-1]["overflows"] == 1  # the step-1 found_inf counted
        assert steps[-1]["hbm"]["count"] >= 0
        return {"ok": True, "records": len(rows)}
    finally:
        os.unlink(path)


def _check_flight() -> dict:
    """ISSUE 14: flight-recorder ring dump round-trip — journal records
    and breadcrumbs ring in memory, an explicit dump lands as strict
    JSON with the HBM snapshot + loss-scale state, tolerant load
    degrades a corrupt file to None, and disarm leaves no global."""
    import jax.numpy as jnp

    from apex_tpu.monitor import flight
    from apex_tpu.monitor.journal import MetricsJournal

    d = tempfile.mkdtemp(prefix="apex_tpu_flight_")
    try:
        jpath = os.path.join(d, "run.jsonl")
        fpath = jpath + ".flight.json"
        fr = flight.arm(fpath, meta={"run": "selftest"}, capacity=64,
                        hooks=False)
        with MetricsJournal(jpath) as j:
            for step in range(3):
                j.step_start()
                j.step_end(step=step,
                           loss=jnp.asarray(2.0 - 0.1 * step, jnp.float32),
                           tokens=1024,
                           metrics={"loss_scale": 2.0 ** 16,
                                    "found_inf": False})
        flight.breadcrumb("comm:psum[data]")
        path = fr.dump("explicit")
        assert path == fpath, path
        import json as _json

        with open(fpath) as f:
            dump = _json.loads(f.read())  # strict JSON by construction
        steps = [r for r in dump["ring"] if r.get("kind") == "step"]
        assert len(steps) == 3 and steps[-1]["step"] == 2, dump["ring"]
        assert dump["last_op"]["op"] == "comm:psum[data]", dump["last_op"]
        assert dump["scaler"]["loss_scale"] == 2.0 ** 16, dump.get("scaler")
        assert isinstance(dump["hbm"], dict), dump.get("hbm")
        assert flight.load(fpath) is not None
        # corrupt dumps degrade to None, never raise
        with open(fpath, "w") as f:
            f.write('{"v": 1, "ring": [tor')
        assert flight.load(fpath) is None
        return {"ok": True, "ring": len(dump["ring"]),
                "last_op": dump["last_op"]["op"]}
    finally:
        flight.disarm()
        import shutil

        shutil.rmtree(d, ignore_errors=True)


def _check_health() -> dict:
    """ISSUE 14: online health rules — a seeded loss spike fires exactly
    the loss-spike rule (online journal wiring AND the offline scan
    agree), a clean journal fires none, and a seeded SLO-burn window
    fires slo-burn."""
    from apex_tpu.monitor import health

    def run(spike: bool):
        recs = [{"kind": "step", "step": s, "loss": 2.0 - 0.01 * s,
                 "tokens_per_sec": 1000.0, "overflows": 0}
                for s in range(12)]
        if spike:
            recs[10]["loss"] = 50.0
        return health.scan(recs)

    assert run(False) == [], run(False)
    fired = run(True)
    assert [a["rule"] for a in fired] == ["loss-spike"], fired
    assert fired[0]["step"] == 10, fired

    # online wiring: the journal streams records through the monitor and
    # appends the alert rows itself
    from apex_tpu.monitor.journal import MetricsJournal

    fd, path = tempfile.mkstemp(prefix="apex_tpu_health_", suffix=".jsonl")
    os.close(fd)
    try:
        with MetricsJournal(path, health=health.HealthMonitor()) as j:
            for s in range(12):
                j.log({"kind": "step", "step": s,
                       "loss": 50.0 if s == 10 else 2.0,
                       "tokens_per_sec": 1000.0, "overflows": 0})
        rows = MetricsJournal.read(path)
        alerts = [r for r in rows if r["kind"] == "alert"]
        assert len(alerts) == 1 and alerts[0]["rule"] == "loss-spike", alerts
    finally:
        os.unlink(path)

    # slo-burn honors the window record's own stamped target
    burn = health.scan([{"kind": "slo", "window": 0, "attainment": 0.5,
                         "target": 0.99}])
    assert [a["rule"] for a in burn] == ["slo-burn"], burn
    return {"ok": True, "spike_rule": fired[0]["rule"],
            "rules": list(health.RULES)}


def _check_watchdog() -> dict:
    from apex_tpu.monitor.watchdog import run_under_watchdog

    # -S skips sitecustomize (which can import an accelerator plugin and
    # take seconds) so the stub children start fast — bench.py test idiom
    healthy = run_under_watchdog(
        [sys.executable, "-S", "-c", "print('alive')"], deadline=30)
    assert healthy.status == "ok" and healthy.returncode == 0, healthy
    assert "alive" in healthy.stdout

    # the child checkpoints, beats once, then wedges: once the beat lands
    # the stall clock restarts from it, so the kill normally arrives well
    # after the checkpoint is durable. A slow interpreter startup (loaded
    # co-tenant host) still races the pre-beat stall window — but at 5 s
    # instead of the old 2 s hard deadline — and the wide deadline is only
    # the backstop, so the dryrun gate is far less flakeable than before
    hang = (
        "import json, os, time\n"
        "with open(os.environ['APEX_TPU_CHECKPOINT_PATH'], 'w') as f:\n"
        "    json.dump({'stage': 'two', 'value': 7}, f)\n"
        "with open(os.environ['APEX_TPU_HEARTBEAT_PATH'], 'w') as f:\n"
        "    json.dump({'ts': time.time(), 'stage': 'two'}, f)\n"
        "time.sleep(60)\n"
    )
    hung = run_under_watchdog([sys.executable, "-S", "-c", hang],
                              deadline=60, stall_timeout=5, poll_s=0.1)
    assert hung.status == "stalled", hung
    assert hung.record == {"stage": "two", "value": 7}, hung.record
    return {"ok": True, "hung_child_recovered_stage": hung.record["stage"]}


def _check_hbm() -> dict:
    import jax.numpy as jnp

    from apex_tpu.monitor.hbm import HBMMonitor, lane_padded_bytes

    # the T(8,128) layout tax: a (512, 1) f32 column pads 128x in lanes
    assert lane_padded_bytes((512, 1), 4) == 512 * 128 * 4

    leak = HBMMonitor()
    leak.sample("baseline")
    retained = []
    for i in range(4):
        retained.append(jnp.ones((256, 256), jnp.float32) * i)
        leak.sample(f"iter{i}")
    growth = leak.growth_bytes()
    assert growth >= 4 * 256 * 256 * 4, growth

    flat = HBMMonitor()
    flat.sample("baseline")
    for i in range(4):
        _ = float(jnp.sum(jnp.ones((256, 256), jnp.float32)))
        flat.sample(f"iter{i}")
    assert abs(flat.growth_bytes()) < 256 * 256 * 4, flat.samples
    del retained
    return {"ok": True, "leak_growth_bytes": growth}


def _check_comms() -> dict:
    import jax
    import jax.numpy as jnp

    from apex_tpu.monitor.comms import comm_accounting
    from apex_tpu.parallel import collectives

    def fn(x):
        y = collectives.psum(x, "i")
        return collectives.pmean(y, "i")

    x = jnp.ones((2, 8, 16), jnp.float32)
    with comm_accounting() as acct:
        # vmap binds the axis name without needing a mesh — trace only
        jax.make_jaxpr(jax.vmap(fn, axis_name="i"))(x)
    per_axis = acct.by_axis()
    expect = 8 * 16 * 4  # per-shard payload of each collective call site
    assert per_axis["i"]["calls"] == 2, per_axis
    assert per_axis["i"]["bytes"] == 2 * expect, per_axis
    return {"ok": True, "by_axis": per_axis}


def _check_mfu() -> dict:
    from apex_tpu.monitor import mfu

    # resolve the table row with any ambient calibration overrides masked
    saved = {k: os.environ.pop(k, None)
             for k in (mfu.ENV_PEAK_FLOPS, mfu.ENV_PEAK_HBM_GBPS)}
    try:
        spec = mfu.peak_spec("tpu v4")
    finally:
        os.environ.update({k: v for k, v in saved.items() if v is not None})
    assert spec["peak_flops"] == 275e12 and spec["source"] == "table:v4", spec
    # roofline join at a hand-computable point: 1 TFLOP + 1 GB in 0.1 s
    m = mfu.mfu_metrics(flops=1e12, bytes_accessed=1e9, wall_s=0.1,
                        tokens=1024, spec=spec)
    assert abs(m["mfu"] - (1e13 / 275e12)) < 1e-4, m  # fields round to 4dp
    assert abs(m["hbm_bw_util"] - (1e10 / 1228e9)) < 1e-4, m
    assert m["bound"] == "compute", m  # t_compute 3.6ms >> t_memory 0.8ms
    # traced costs: one (8,16)x(16,4) matmul = 2*8*4*16 flops via the
    # pyprof jaxpr walk (no compile needed)
    import jax.numpy as jnp

    costs = mfu.traced_step_costs(
        lambda a, b: a @ b, jnp.ones((8, 16)), jnp.ones((16, 4)))
    assert costs["flops"] == 2 * 8 * 4 * 16, costs
    return {"ok": True, "mfu_at_point": m["mfu"], "bound": m["bound"]}


def _check_diagnose() -> dict:
    import jax
    import jax.numpy as jnp

    from apex_tpu.monitor.diagnose import OverflowForensics, RecompileTracker
    from apex_tpu.monitor.journal import MetricsJournal

    fd, path = tempfile.mkstemp(prefix="apex_tpu_diag_", suffix=".jsonl")
    os.close(fd)
    try:
        with MetricsJournal(path) as j:
            forensics = OverflowForensics(j)
            for step in range(6):
                forensics.observe(step=step, loss=2.0 - 0.01 * step,
                                  metrics={"loss_scale": 2.0 ** 16,
                                           "found_inf": False})
            rec = forensics.observe(
                step=6, loss=float("nan"),
                metrics={"found_inf": True, "loss_scale": 2.0 ** 15,
                         "grad_norm_by_group": {"wte": 1.5,
                                                "layers": float("inf")}})
            assert rec is not None and rec["trigger"] == "overflow", rec
            assert rec["nonfinite_groups"] == ["layers"], rec

            tracker = RecompileTracker(j)
            fn = tracker.wrap(jax.jit(lambda x: x * 2), name="double")
            fn(jnp.ones((4,)))
            fn(jnp.ones((4,)))   # cache hit
            fn(jnp.ones((8,)))   # fresh shape: miss
            s = tracker.summary()["double"]
            assert s == dict(s, calls=3, compiles=2, signatures=2), s
        rows = MetricsJournal.read(path)
        kinds = [r["kind"] for r in rows]
        assert kinds.count("forensics") == 1 and kinds.count("recompile") == 2
        f_row = next(r for r in rows if r["kind"] == "forensics")
        # journal sanitization: the inf group norm became null + a key path
        assert f_row["grad_norm_by_group"]["layers"] is None
        assert any("layers" in k for k in f_row["nonfinite_keys"])
        return {"ok": True, "trigger": rec["trigger"],
                "recompiles": s["compiles"]}
    finally:
        os.unlink(path)


def _check_report() -> dict:
    from apex_tpu.monitor import report
    from apex_tpu.monitor.journal import MetricsJournal

    def write_run(path, rate):
        with MetricsJournal(path) as j:
            for step in range(8):
                j.log({"kind": "step", "step": step, "wall_s": 0.1,
                       "loss": 2.0 - 0.05 * step, "tokens": 1024,
                       "tokens_per_sec": rate, "overflows": 0})

    d = tempfile.mkdtemp(prefix="apex_tpu_report_")
    try:
        a, b = os.path.join(d, "a.jsonl"), os.path.join(d, "b.jsonl")
        write_run(a, 1000.0)
        write_run(b, 800.0)  # 20% regression
        analysis = report.analyze(MetricsJournal.read(a))
        assert analysis["step_records"] == 8
        assert analysis["tokens_per_sec"]["p50"] == 1000.0, analysis
        # CLI modes, with their prints swallowed (this selftest's contract
        # is ONE JSON line on stdout)
        import contextlib
        import io

        with contextlib.redirect_stdout(io.StringIO()):
            assert report.main([a]) == 0
            assert report.main(["compare", a, a, "--threshold", "0.05"]) == 0
            assert report.main(["compare", a, b, "--threshold", "0.05"]) == 1
        return {"ok": True, "p50": analysis["tokens_per_sec"]["p50"]}
    finally:
        import shutil

        shutil.rmtree(d, ignore_errors=True)


def _check_lint() -> dict:
    import jax.numpy as jnp
    from jax import lax

    from apex_tpu import lint
    from apex_tpu.lint import trace as lint_trace
    from apex_tpu.utils.compat import ensure_jax_compat

    ensure_jax_compat()  # jax<0.5: the MoE dispatch fixture uses axis_size

    # engine 1: the tree itself must lint clean, with every suppression
    # carrying a justification (the same contract tests/test_lint.py
    # enforces in tier-1; here it also rides dryrun_multichip)
    rep = lint.run_paths()
    assert not rep.errors, [f.format() for f in rep.errors[:5]]
    assert rep.files_scanned >= 100, rep.files_scanned
    assert set(rep.rules_run) == set(lint.RULES), rep.rules_run
    assert all(f.justification for f in rep.suppressed), [
        f.format() for f in rep.suppressed if not f.justification]

    # engine 2, lane padding: the calibrated taxes — d=32 pads 4x to 128
    # lanes; a (512, 1) f32 column occupies 512*128*4 bytes
    pad = lint_trace.lane_padding_report(
        lambda q, w: (q * 2.0).sum() + w.sum(),
        jnp.ones((2, 4, 128, 32), jnp.float32),
        jnp.ones((512, 1), jnp.float32), min_bytes=0)
    by_shape = {tuple(f["shape"]): f for f in pad["findings"]}
    assert by_shape[(2, 4, 128, 32)]["waste_ratio"] == 4.0, pad
    assert by_shape[(512, 1)]["padded_bytes"] == 512 * 128 * 4, pad

    # engine 2, transpose hazard: bare pmean(loss) under grad leaves an
    # extra scalar collective in the backward; the identity-backward psum
    # (the pipeline loss-aggregation wrapper) leaves none
    from apex_tpu.transformer.tensor_parallel.mappings import (
        reduce_from_tensor_model_parallel_region)

    def bare(x):
        return lax.pmean(jnp.sum(x * x), "i")

    def wrapped(x):
        return reduce_from_tensor_model_parallel_region(jnp.sum(x * x), "i")

    x = jnp.ones((4,), jnp.float32)
    hz = lint_trace.transpose_hazards(bare, x, axes={"i": 8})
    assert hz["hazard"] and hz["extra_in_backward"], hz
    assert not lint_trace.transpose_hazards(wrapped, x, axes={"i": 8})["hazard"]

    # engine 2, recompile scan: python scalars and weak-typed leaves are
    # named by pytree path; committed arrays pass
    haz = lint_trace.recompile_hazards(
        {"scale": 2.0, "x": jnp.ones((2,), jnp.float32)},
        weak=jnp.asarray(1.0))
    assert sorted(h["kind"] for h in haz) == ["python-scalar", "weak-type"], haz

    # engine 2, ZeRO tripwire: a full-size grad psum on the data axis is
    # the double-reduction regression; the optimizer's decomposed
    # psum_scatter/all_gather chunk path passes (scalar loss/overflow
    # collectives are exempt)
    from apex_tpu.optimizers.distributed import gather_leaf, scatter_chunk

    big = jnp.ones((64, 128), jnp.float32)  # 8192 elems: bulk
    zr_bad = lint_trace.zero_redundancy_hazards(
        lambda g: lax.psum(g, "data") + lax.pmax(jnp.sum(g), "data"),
        big, axes={"data": 8})
    assert zr_bad["hazard"] and zr_bad["bulk_psums"] == 1, zr_bad
    assert zr_bad["census"]["other"].get("pmax") == 1, zr_bad

    def zr_good(g):
        chunk = scatter_chunk(g, 8, "data") / 8
        return gather_leaf(chunk, g.shape, g.dtype, "data",
                           gather_dtype=jnp.bfloat16)

    zr_ok = lint_trace.zero_redundancy_hazards(zr_good, big,
                                               axes={"data": 8})
    assert not zr_ok["hazard"], zr_ok
    assert zr_ok["census"]["bulk"].get("reduce_scatter") == 1, zr_ok

    # engine 2, ZeRO-3 tripwire: a whole-stack (model-sized) param gather
    # in a fully-sharded step is the O(model) rematerialization; per-layer
    # JIT gathers pass
    from apex_tpu.optimizers.distributed import gather_stacked_leaf

    L, row = 8, (8, 64)  # 512 elems/layer, 4096 total
    chunks = jnp.ones((L, 64), jnp.float32)  # (L, k) at n=8

    z3_bad = lint_trace.zero3_gather_hazards(
        lambda c: gather_stacked_leaf(c, row, jnp.float32, "data"),
        chunks, axes={"data": 8}, model_elems=L * 512)
    assert z3_bad["hazard"] and z3_bad["bulk_gathers"] == 1, z3_bad

    def z3_good(c):
        return jnp.stack([gather_leaf(c[i], row, jnp.float32, "data")
                          for i in range(L)])

    z3_ok = lint_trace.zero3_gather_hazards(z3_good, chunks,
                                            axes={"data": 8},
                                            model_elems=L * 512)
    assert not z3_ok["hazard"] and z3_ok["layer_gathers"] == L, z3_ok

    # engine 2, ZeRO-3 gather-prefetch tripwire: per-layer gathers INSIDE
    # rematerialized bodies (the serialized unrolled drive) are pinned to
    # their layer's schedule; gathers standing free ahead of the compute
    # (the zero3_prefetch double-buffered drive) pass
    import jax as _jax

    row = (16, 16)
    chunks8 = jnp.ones((4, 32), jnp.float32)  # 4 layers, k=32 at n=8

    def _serialized(c, h):
        for i in range(4):
            body = _jax.checkpoint(
                lambda ci, hh: jnp.tanh(
                    hh @ gather_leaf(ci, row, jnp.float32, "data")))
            h = body(c[i], h)
        return jnp.sum(h * h)

    def _prefetched(c, h):
        gathered = [gather_leaf(c[i], row, jnp.float32, "data")
                    for i in range(4)]
        for p in gathered:
            h = jnp.tanh(h @ p)
        return jnp.sum(h * h)

    h0 = jnp.ones((2, 16), jnp.float32)
    pg_bad = lint_trace.unprefetched_gather_hazards(
        _jax.grad(_serialized, argnums=0), chunks8, h0, axes={"data": 8})
    assert pg_bad["hazard"] and pg_bad["fused_gathers"] >= 2, pg_bad
    pg_ok = lint_trace.unprefetched_gather_hazards(
        _jax.grad(_prefetched, argnums=0), chunks8, h0, axes={"data": 8})
    assert not pg_ok["hazard"] and pg_ok["free_gathers"] >= 4, pg_ok

    # engine 2, quantized-collective tripwire: a surviving fp32 bulk
    # reduce payload in a step that requests a quantized grad reduce is
    # the fat-wire regression; the encoded all_to_all pair passes, and a
    # quantized grad reduce with no residual leaf flags the EF check
    from apex_tpu.parallel.quantize import quantized_reduce_scatter

    qc_bad = lint_trace.quantized_comm_hazards(
        lambda g: scatter_chunk(g, 8, "data") / 8, big, axes={"data": 8})
    assert qc_bad["hazard"] and qc_bad["fat_reduces"] == 1, qc_bad

    def qc_good(g):
        chunk, _ = quantized_reduce_scatter(g, 8, "data", "int8")
        return chunk / 8

    qc_ok = lint_trace.quantized_comm_hazards(
        qc_good, big, axes={"data": 8}, residual={"err": {}})
    assert not qc_ok["hazard"] and qc_ok["quantized_reduces"] == 1, qc_ok
    qc_nores = lint_trace.quantized_comm_hazards(
        qc_good, big, axes={"data": 8}, residual=None)
    assert qc_nores["hazard"] and qc_nores["findings"][0][
        "rule"] == "quantized-comm-no-residual", qc_nores

    # engine 2, MoE dispatch tripwire (ISSUE 15): an expert-parallel MoE
    # layer's all_to_all dispatch passes (and its int8 wire passes the
    # fat-wire check); a replicated-expert run of the SAME layer under an
    # expert-parallel request is flagged, as is an fp32 dispatch under a
    # quantized-wire request. The rank-2 ZeRO grad all_to_alls on the
    # same axis never pollute the dispatch census.
    from apex_tpu.transformer.moe import MoEMLP

    moe = MoEMLP(8, 16, num_experts=8, top_k=2, capacity_factor=2.0,
                 expert_axis="data")
    moe_q = MoEMLP(8, 16, num_experts=8, top_k=2, capacity_factor=2.0,
                   expert_axis="data", dispatch_dtype="int8")
    mp = moe.init(_jax.random.PRNGKey(0))
    mp_local = {"router": mp["router"],
                "fc1": _jax.tree.map(lambda v: v[:1], mp["fc1"]),
                "fc2": _jax.tree.map(lambda v: v[:1], mp["fc2"])}
    # 256 tokens -> (E=8, C=128, d=8) buckets: 8192 elems, over the bulk
    # floor (a smaller batch's dispatch would be filtered as side-channel)
    xtok = jnp.ones((256, 8), jnp.float32)
    md_ok = lint_trace.moe_dispatch_hazards(
        moe.apply_expert_parallel, mp_local, xtok, axes={"data": 8})
    assert not md_ok["hazard"] and md_ok["dispatch_all_to_alls"] == 2, md_ok
    md_bad = lint_trace.moe_dispatch_hazards(
        moe.apply, mp, xtok, axes={"data": 8})
    assert md_bad["hazard"] and md_bad["findings"][0][
        "rule"] == "moe-dispatch-missing", md_bad
    md_fat = lint_trace.moe_dispatch_hazards(
        moe.apply_expert_parallel, mp_local, xtok, axes={"data": 8},
        wire_dtype="int8")
    assert md_fat["hazard"] and md_fat["findings"][0][
        "rule"] == "moe-dispatch-fat-wire", md_fat
    md_q = lint_trace.moe_dispatch_hazards(
        moe_q.apply_expert_parallel, mp_local, xtok, axes={"data": 8},
        wire_dtype="int8")
    assert not md_q["hazard"] and md_q["dispatch_all_to_alls"] == 2, md_q
    # the quantized ZeRO grad reduce's rank-2 all_to_alls land in the
    # chunk bucket, not the dispatch census
    md_chunk = lint_trace.moe_dispatch_hazards(
        qc_good, big, axes={"data": 8})
    assert md_chunk["census"]["chunk"] and not md_chunk[
        "census"]["dispatch"], md_chunk

    # engine 2, sequence-parallel tripwire: an activation psum on the TP
    # axis is the regression; the reduce_scatter/all_gather conjugates and
    # CE-shaped rank-2 psums pass
    from apex_tpu.transformer.tensor_parallel.mappings import (
        gather_from_sequence_parallel_region,
        reduce_scatter_to_sequence_parallel_region)

    act = jnp.ones((2, 8, 4), jnp.float32)
    sp_bad = lint_trace.sequence_parallel_hazards(
        lambda a: lax.psum(a, "model") * 2.0, act, axes={"model": 4})
    assert sp_bad["hazard"] and sp_bad["activation_psums"] == 1, sp_bad
    sp_ok = lint_trace.sequence_parallel_hazards(
        lambda a: gather_from_sequence_parallel_region(
            reduce_scatter_to_sequence_parallel_region(a, "model"), "model"),
        act, axes={"model": 4})
    assert not sp_ok["hazard"], sp_ok
    assert sp_ok["census"]["activation"].get("reduce_scatter") == 1, sp_ok
    return {"ok": True, "files": rep.files_scanned,
            "suppressed": len(rep.suppressed),
            "padding_waste_bytes": pad["waste_bytes"]}


def _check_tracing() -> dict:
    import json as _json
    import math

    import jax
    import jax.numpy as jnp

    from apex_tpu.lint import trace as lint_trace
    from apex_tpu.monitor import tracing
    from apex_tpu.utils.compat import ensure_jax_compat

    ensure_jax_compat()  # jax<0.5: the ring fixture uses lax.axis_size

    # nested spans: depths recorded, barrier stops the clock on a fetch
    tr = tracing.Tracer(None, meta={"run": "selftest"})
    with tr.span("step", step=0) as outer:
        with tr.span("zero.grads", cat="compute") as sp:
            sp.barrier(jnp.ones((4,)))
        outer.barrier(jnp.zeros(()))
    spans = [r for r in tr.records if r["kind"] == "span"]
    assert [s["name"] for s in spans] == ["zero.grads", "step"], spans
    assert spans[0]["depth"] == 1 and spans[1]["depth"] == 0, spans
    assert all(s["dur_s"] >= 0 for s in spans), spans

    # strict JSON: a non-finite attr value sanitizes to null + key path
    rec = tr.record("bad", dur_s=0.25, cat="host", metric=float("inf"))
    assert rec["metric"] is None and "metric" in rec["nonfinite_keys"], rec
    _json.loads(_json.dumps(rec))  # must be strict-parseable

    # torn trace files parse (journal read semantics shared verbatim)
    fd, path = tempfile.mkstemp(prefix="apex_tpu_trace_", suffix=".jsonl")
    os.close(fd)
    try:
        with tracing.Tracer(path) as ftr:
            with ftr.span("a"):
                pass
        with open(path, "a") as f:
            f.write('{"kind": "span", "trunc')
        rows = tracing.Tracer.read(path)
        assert rows.truncated and rows.bad_lines == 1 and len(rows) == 1, rows
    finally:
        os.unlink(path)

    # analytic floors at hand-computable points: the SPMD ring's
    # (S-1)/(vpp*M+S-1), 1F1B's (S-1)/(M+S-1), and the zero-bubble
    # W/B-split floor (S-1)/(3M+S-1) — the greedy planner must COUNT the
    # same fraction its closed form claims (schedule-as-data: the plan is
    # the ground truth)
    ebf = tracing.expected_bubble_fraction
    assert abs(ebf("interleaved", 8, 4, 2) - 3 / 19) < 1e-12
    assert abs(ebf("1f1b", 8, 4) - 3 / 11) < 1e-12
    assert abs(ebf("zero-bubble", 8, 4) - 3 / 27) < 1e-12
    assert ebf("interleaved", 8, 1) == 0.0  # no pipeline, no bubble
    from apex_tpu.transformer.pipeline_parallel import plan_schedule

    for sched in ("gpipe", "1f1b", "zero-bubble"):
        plan = plan_schedule(sched, 8, 4)
        assert abs(plan.bubble_fraction() - ebf(sched, 8, 4)) < 1e-12, (
            sched, plan.bubble_fraction())

    # anatomy invariant at a hand point: 0.06s compute + 0.06s comm in a
    # 0.1s wall → 0.02s overlapped (1/3 of the cheaper side), fractions
    # summing to exactly 1.0
    an = tracing.step_anatomy(wall_s=0.1, compute_s=0.06, comm_s=0.06)
    assert abs(an["overlap_fraction"] - 1 / 3) < 1e-3, an
    assert abs(an["compute_frac"] + an["comm_frac"]
               + an["stall_frac"] - 1.0) < 1e-6, an

    # synthetic 2-rank slot timeline: M=3 units, S=2 → 4 ticks, 1 idle
    # slot per rank per direction → measured bubble = 1/4 exactly
    syn = tracing.Tracer(None)
    for phase in ("fwd", "bwd"):
        for t in range(4):
            for s in range(2):
                live = 0 <= t - s < 3
                syn.record(phase if live else "bubble", dur_s=0.01,
                           cat="pipe", rank=s, tick=t, phase=phase,
                           microbatch=(t - s) if live else None)
    pa = tracing.pipeline_anatomy(syn.records)
    assert abs(pa["bubble_fraction"]["mean"] - 0.25) < 1e-6, pa
    assert abs(pa["bubble_fraction"]["mean"]
               - ebf("1f1b", 3, 2)) < 1e-6, pa

    # Chrome export round-trips json with one complete event per span
    # plus per-rank process metadata
    trace = _json.loads(_json.dumps(tracing.chrome_trace(syn.records)))
    ev = trace["traceEvents"]
    assert len([e for e in ev if e["ph"] == "X"]) == 16, len(ev)
    assert {e["pid"] for e in ev} == {0, 1}, ev
    assert all(e["ts"] >= 0 and e.get("dur", 0) >= 0 for e in ev
               if e["ph"] == "X"), ev
    assert not any(math.isnan(e["ts"]) for e in ev if e["ph"] == "X")

    # untimed-schedule tripwire: a compiled ring drive under an armed
    # tracer with no spans is the census-only regression; a drive that
    # emits pipe spans passes
    from apex_tpu.transformer.pipeline_parallel import schedules

    run_stage = lambda lp, h: h * (1.0 + jnp.sum(lp))  # noqa: E731
    layers_l = jnp.ones((4, 2, 2))
    h_mb = jnp.ones((4, 3, 5))
    ring = jax.vmap(
        lambda ll, hm: schedules._pipeline_ring(run_stage, ll, hm, "i"),
        axis_name="i")

    bad = lint_trace.untimed_schedule_hazards(
        lambda: jax.make_jaxpr(ring)(layers_l, h_mb))
    assert bad["hazard"] and bad["drives"] == 1, bad
    assert bad["findings"][0]["rule"] == "untimed-schedule", bad

    def timed_drive():
        from apex_tpu.monitor import tracing as tmod

        jax.make_jaxpr(ring)(layers_l, h_mb)
        tmod.get_tracer().record("fwd", dur_s=0.01, cat="pipe", rank=0)

    ok = lint_trace.untimed_schedule_hazards(timed_drive)
    assert not ok["hazard"] and ok["pipe_spans"] == 1, ok
    return {"ok": True, "spans": len(spans),
            "synthetic_bubble": pa["bubble_fraction"]["mean"],
            "chrome_events": len(ev)}


def _check_serve() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.lint.trace import decode_recompile_hazards
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.monitor.journal import MetricsJournal
    from apex_tpu.serve import Engine, Request, ServeConfig

    # engine smoke (serial build — runs on any device count; the TP-sharded
    # build rides dryrun_multichip's serve config + tier-1): greedy decode
    # through the paged cache must reproduce the full-context forward's
    # argmax at every generated position — the serve equivalence gate
    cfg = GPTConfig(vocab_size=41, hidden_size=16, num_layers=1,
                    num_attention_heads=2, max_seq_len=32,
                    hidden_dropout=0.0, axis=None,
                    compute_dtype=jnp.float32, remat=False)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    eng = Engine(model, params,
                 ServeConfig(max_batch=2, max_seq=24, block_size=8))
    fd, path = tempfile.mkstemp(prefix="apex_tpu_serve_", suffix=".jsonl")
    os.close(fd)
    try:
        with MetricsJournal(path) as j:
            res = eng.run([Request(prompt=[3, 1, 4, 1, 5], max_new_tokens=4,
                                   request_id="a"),
                           Request(prompt=[2, 7], max_new_tokens=3,
                                   request_id="b")], journal=j)
        assert set(res) == {"a", "b"}, res
        for req in res.values():
            seq = list(req.prompt) + req.tokens
            ref = jnp.argmax(
                model.apply(params, jnp.asarray([seq], jnp.int32))[0], -1)
            want = [int(v) for v in np.asarray(ref)[len(req.prompt) - 1:-1]]
            assert req.tokens == want, (req.request_id, req.tokens, want)
        # continuous batching released every page and slot
        assert eng.allocator.used == 0 and eng.batcher.idle
        rows = MetricsJournal.read(path)
        kinds = [r["kind"] for r in rows]
        assert kinds.count("request") == 2 and "step" in kinds, kinds
        from apex_tpu.monitor import report as report_mod

        sv = report_mod.analyze(rows).get("serving")
        assert sv and sv["requests"] == 2 and "ttft_ms" in sv, sv
    finally:
        os.unlink(path)

    # the decode-recompile tripwire: the engine's REAL tick argument
    # stream is shape-stable; a growing per-request KV tensor is flagged
    clean = decode_recompile_hazards(eng.decode_args, ticks=3)
    assert not clean["hazard"], clean["findings"][:2]

    grow = decode_recompile_hazards(
        lambda t: (jnp.ones((1, 2, t + 1, 4), jnp.float32),
                   jnp.zeros((2,), jnp.int32)), ticks=2)
    assert grow["hazard"], grow
    assert grow["findings"][0]["rule"] == "decode-shape-churn", grow

    # ISSUE 12: shared-prefix pair — the second request must SKIP prefill
    # to the divergence point (prompt blocks shared by reference), decode
    # exactly, and release every page once the cache drops its refs
    eng2 = Engine(model, params,
                  ServeConfig(max_batch=2, max_seq=24, block_size=8,
                              prefix_cache=True, spec_k=2))
    base = [3, 1, 4, 1, 5, 9, 2, 6]  # one full block
    res2 = eng2.run([Request(prompt=base + [5, 3], max_new_tokens=3,
                             request_id="p"),
                     Request(prompt=base + [8, 9, 7], max_new_tokens=3,
                             request_id="q")])
    for req in res2.values():
        seq = list(req.prompt) + req.tokens
        ref = jnp.argmax(
            model.apply(params, jnp.asarray([seq], jnp.int32))[0], -1)
        want = [int(v) for v in np.asarray(ref)[len(req.prompt) - 1:-1]]
        assert req.tokens == want, (req.request_id, req.tokens, want)
    assert res2["q"].cached_tokens >= len(base), res2["q"].cached_tokens
    assert eng2.stats["tokens_reused"] >= len(base), eng2.stats
    eng2.drop_prefix_cache()
    assert eng2.allocator.used == 0 and eng2.batcher.idle  # zero leaks

    # the extended tripwire covers the chunked-prefill and speculative-
    # verify streams both ways: the real streams pass, a growing chunk
    # width / python-int draft length is flagged with its stream name
    multi = decode_recompile_hazards(
        eng2.decode_args, ticks=3,
        extra_streams={"chunk": eng2.chunk_args, "verify": eng2.spec_args})
    assert not multi["hazard"], multi["findings"][:2]
    assert multi["stream_leaves"]["chunk"] > 0
    assert multi["stream_leaves"]["verify"] > 0
    bad = decode_recompile_hazards(
        eng2.decode_args, ticks=2,
        extra_streams={"chunk": lambda t: (
            jnp.zeros((1, 8 * (t + 1)), jnp.int32),),
            "verify": lambda t: (jnp.zeros((2, 3), jnp.int32), t)})
    assert bad["hazard"], bad
    rules = {(f["stream"], f["rule"]) for f in bad["findings"]}
    assert ("chunk", "decode-shape-churn") in rules, rules
    assert ("verify", "recompile-hazard") in rules, rules
    return {"ok": True, "requests": len(res),
            "decode_leaves": clean["leaves"],
            "prefix_cached_tokens": int(res2["q"].cached_tokens),
            "spec_accepted_mean": eng2.stats["mean_accepted_len"]}


def _check_reqtrace() -> dict:
    """Request-scoped serving traces (ISSUE 17): every SLO violator keeps
    its full span tree, compliant requests sample deterministically 1-in-N
    with the rest folding into ONE bounded reqhist record, per-request
    TTFT/ITL attribution fractions sum to 1.0, and a disarmed engine
    produces identical token streams (the byte-identity discipline)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.monitor import tracing
    from apex_tpu.serve import Engine, Request, ServeConfig

    cfg = GPTConfig(vocab_size=41, hidden_size=16, num_layers=1,
                    num_attention_heads=2, max_seq_len=32,
                    hidden_dropout=0.0, axis=None,
                    compute_dtype=jnp.float32, remat=False)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    scfg = dict(max_batch=2, max_seq=24, block_size=8)

    def reqs():
        return [Request(prompt=[3, 1, 4, 1, 5], max_new_tokens=4,
                        request_id="a"),
                Request(prompt=[2, 7], max_new_tokens=3, request_id="b"),
                Request(prompt=[6, 2, 8], max_new_tokens=3,
                        request_id="c")]

    def frac_sums(req):
        for cls in ("ttft", "itl"):
            fr = (req.attribution or {}).get(cls)
            if fr:
                s = sum(v for k, v in fr.items() if k.endswith("_frac"))
                assert abs(s - 1.0) < 1e-3, (req.request_id, cls, fr)

    # tail sampling keeps 100% of violators even at a huge sample stride
    eng = Engine(model, params,
                 ServeConfig(slo_itl_ms=1e-6, trace_sample_n=10 ** 6,
                             **scfg))
    tr = tracing.Tracer(None, keep=True)
    with tracing.scoped(tr):
        res = eng.run(reqs())
    roots = [r for r in tr.records if r.get("name") == "serve.request"]
    assert len(roots) == 3 and eng.trace_violators == 3, (
        len(roots), eng.trace_violators)
    kids = [r for r in tr.records
            if r.get("cat") == "serve-req" and r.get("depth") == 1]
    assert kids and all(r.get("request") for r in kids), kids[:2]
    for req in res.values():
        assert (req.trace or {}).get("trace_id"), req.request_id
        frac_sums(req)

    # compliant requests: deterministic 1-in-2 sample (= ceil(3/2) trees)
    # + exactly one bounded histogram record for the rest
    eng2 = Engine(model, params,
                  ServeConfig(slo_itl_ms=1e9, trace_sample_n=2, **scfg))
    tr2 = tracing.Tracer(None, keep=True)
    with tracing.scoped(tr2):
        eng2.run(reqs())
    roots2 = [r for r in tr2.records if r.get("name") == "serve.request"]
    hist = [r for r in tr2.records if r.get("kind") == "reqhist"]
    assert len(roots2) == 2 and len(hist) == 1, (len(roots2), len(hist))
    assert "ttft" in hist[0]["phases"], hist[0]["phases"].keys()

    # disarmed: identical token streams, attribution still stamped
    eng3 = Engine(model, params, ServeConfig(**scfg))
    res3 = eng3.run(reqs())
    assert all(res3[k].tokens == res[k].tokens for k in res3), "drift"
    for req in res3.values():
        frac_sums(req)
    return {"ok": True, "violator_roots": len(roots),
            "sampled_roots": len(roots2),
            "hist_phases": len(hist[0]["phases"])}


def _check_audit() -> dict:
    """The whole-program step-audit gate (ISSUE 13): every registered IR
    pass (collective-consistency / static-hbm / dtype-drift / comm-bytes)
    plus the program-relevant tripwires over the small dense + zero
    canonical train steps, each traced ONCE on the shared walker
    (apex_tpu.lint.ir) — the same verdict `python -m apex_tpu.lint.audit`
    emits, gating all_ok here so telemetry CI fails the moment a step
    program stops auditing clean."""
    from apex_tpu.lint import audit as lint_audit
    from apex_tpu.lint import ir as ir_mod

    verdict = lint_audit.run_audit(programs=("dense", "zero"))
    assert verdict["all_ok"], verdict
    dense = verdict["programs"]["dense"]
    # the passes actually ran over a real walk, not a vacuous one
    assert set(dense["passes"]) == set(ir_mod.PASS_REGISTRY), dense
    cc = dense["passes"]["collective-consistency"]
    assert cc["collectives"] > 0 and cc["ppermutes_checked"] > 0, cc
    hbm = dense["passes"]["static-hbm"]
    assert hbm["peak_bytes"] >= hbm["resident_in_bytes"] > 0, hbm
    zero = verdict["programs"]["zero"]
    assert not zero["tripwires"]["zero-redundancy"]["hazard"], zero
    return {"ok": True, "programs": sorted(verdict["programs"]),
            "errors": verdict["errors"],
            "suppressed": verdict["suppressed"],
            "dense_peak_bytes": hbm["peak_bytes"]}


def _check_ledger() -> dict:
    """The run ledger + calibration loop (ISSUE 16): appends round-trip
    through the crash-tolerant reader, trend groups by fingerprint, the
    N-run regress gate passes its own history and exits non-zero on a
    seeded throughput drop, and a fitted calibration file round-trips
    and (armed) outranks the APEX_TPU_PEAK_* env overrides."""
    import contextlib
    import io
    import shutil

    from apex_tpu.monitor import calibrate, ledger

    d = tempfile.mkdtemp(prefix="apex_tpu_ledger_")
    try:
        path = os.path.join(d, "ledger.jsonl")

        def rec(rate):
            return {"kind": "run", "run": "selftest",
                    "config": {"tp": 2},
                    "fingerprint": ledger.config_fingerprint({"tp": 2}),
                    "measured": {"step_records": 4,
                                 "tokens_per_sec": {"p50": rate},
                                 "wall_s": {"p50": 0.1}},
                    "predicted": {"flops_per_step": 2e11}}

        for _ in range(3):
            ledger.append(path, rec(1000.0))
        rows = ledger.read(path)
        tr = ledger.trend(rows)
        assert len(tr) == 1 and len(next(iter(tr.values()))["rows"]) == 3, tr

        # self-history passes; a seeded 30% throughput drop exits 1
        assert ledger.regress(rows)["ok"]
        with contextlib.redirect_stdout(io.StringIO()):
            assert ledger.main(["regress", path]) == 0
        ledger.append(path, rec(700.0))
        with contextlib.redirect_stdout(io.StringIO()):
            assert ledger.main(["regress", path, "--format", "json"]) == 1
        res = ledger.regress(ledger.read(path))
        assert res["regressed"] == ["tokens_per_sec_p50"], res

        # a ledger torn by a kill mid-write still parses (and flags it)
        with open(path, "a") as f:
            f.write('{"kind": "run", "torn')
        rows = ledger.read(path)
        assert len(rows) == 4 and rows.truncated, (len(rows), rows.truncated)

        # calibrate: fit → save → armed file outranks the env knob
        fit = calibrate.fit(rows)
        assert fit["peak_flops"] == 2e12, fit  # 2e11 flops / 0.1 s
        cal_path = calibrate.save(os.path.join(d, "cal.json"), fit)
        saved = {k: os.environ.pop(k, None)
                 for k in ("APEX_TPU_PEAK_FLOPS", calibrate.ENV_CALIBRATION)}
        try:
            os.environ["APEX_TPU_PEAK_FLOPS"] = "9e99"
            os.environ[calibrate.ENV_CALIBRATION] = cal_path
            from apex_tpu.monitor import mfu

            spec = mfu.peak_spec("tpu v4")
            assert spec["peak_flops"] == 2e12, spec
            assert "calibrated" in spec["source"], spec
        finally:
            for k, v in saved.items():
                os.environ.pop(k, None)
                if v is not None:
                    os.environ[k] = v
        return {"ok": True, "runs": len(ledger.read(path)),
                "regressed": res["regressed"],
                "fitted_peak_flops": fit["peak_flops"]}
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _check_plan() -> dict:
    """The auto-parallelism planner (ISSUE 18): a tiny search ranks
    candidates off-TPU with the full predicted anatomy on every record,
    an impossible budget rejects EVERYTHING with static-hbm provenance
    (no silent empty tables), and the ``plan`` audit program — the
    winner's claimed step traced and checked by the ``plan-feasibility``
    IR pass — audits clean end to end."""
    from apex_tpu import plan as plan_mod
    from apex_tpu.lint import audit as lint_audit

    spec = plan_mod.ModelSpec("selftest-tiny", 128, 64, 4, 4, 32)
    result = plan_mod.search(spec, mesh=8, hbm_gb=16.0, platform="cpu")
    assert result["winner"], result["rejected"][:3]
    for rec in result["ranked"]:
        pred = rec["predicted"]
        assert pred["hbm_bytes"] > 0 and pred["step_seconds"] > 0, rec
        assert "ici" in pred["comm_bytes_by_tier"], rec
        assert 0.0 <= pred["bubble_floor"] < 1.0, rec

    # a budget nothing fits must reject every candidate WITH provenance
    broke = plan_mod.search(spec, mesh=8, hbm_bytes=1 << 10,
                            platform="cpu")
    assert broke["winner"] is None, broke["winner"]
    assert broke["rejected"], "empty rejection table"
    assert all(r["rejected_by"] for r in broke["rejected"]), broke

    verdict = lint_audit.run_audit(programs=("plan",))
    assert verdict["all_ok"], verdict
    feas = verdict["programs"]["plan"]["passes"]["plan-feasibility"]
    assert feas["audited"] and not feas["findings"], feas
    return {"ok": True, "ranked": len(result["ranked"]),
            "rejected": len(broke["rejected"]),
            "winner_zero": result["winner"]["candidate"]["zero_level"]}


def _check_pod() -> dict:
    """Pod-scale two-tier wire (ISSUE 19): the modeled DCN row resolves
    (and honors its env override), step-anatomy splits exposed comm per
    link class without moving the fraction invariant, the flat-DCN
    tripwire flags the tuple-axis bulk collective while the hierarchical
    stages and scalar collectives pass, and the ``pod`` canonical audit
    program (hierarchical ZeRO apply, int8 DCN wire) audits clean."""
    import jax.numpy as jnp
    from jax import lax

    from apex_tpu.lint import audit as lint_audit
    from apex_tpu.lint import trace as lint_trace
    from apex_tpu.monitor import tracing
    from apex_tpu.parallel import hierarchy
    from apex_tpu.utils.compat import ensure_jax_compat

    ensure_jax_compat()  # jax<0.5: the hierarchy stages use axis_size

    # the modeled DCN row: table-resolved, env-overridable
    saved = os.environ.pop(tracing.ENV_PEAK_DCN_GBPS, None)
    try:
        spec = tracing.dcn_spec("tpu v4")
        assert spec["dcn_bytes_per_sec"] > 0, spec
        assert spec["source"].startswith("table"), spec
        os.environ[tracing.ENV_PEAK_DCN_GBPS] = "2"
        over = tracing.dcn_spec("tpu v4")
        assert over["dcn_bytes_per_sec"] == 2e9, over
        assert "env" in over["source"], over
    finally:
        os.environ.pop(tracing.ENV_PEAK_DCN_GBPS, None)
        if saved is not None:
            os.environ[tracing.ENV_PEAK_DCN_GBPS] = saved

    # tiered anatomy: ici_s + dcn_s == exposed comm, invariant unmoved
    an = tracing.step_anatomy(
        wall_s=0.1, flops=1e6, comm_bytes=5e8, dcn_bytes=5e8,
        spec={"peak_flops": 1e12, "peak_hbm_bytes_per_sec": 1e12,
              "source": "test"},
        ici={"ici_bytes_per_sec": 1e10, "source": "test"},
        dcn={"dcn_bytes_per_sec": 1e9, "source": "test"})
    assert abs(an["ici_s"] + an["dcn_s"]
               - an["exposed_comm_s"]) < 1e-9, an
    assert an["dcn_s"] > an["ici_s"], an  # the slow tier dominates
    assert abs(an["compute_frac"] + an["comm_frac"]
               + an["stall_frac"] - 1.0) < 1e-6, an

    # the flat-DCN tripwire: one tuple-axis bulk collective ships the
    # full payload across the slow tier; the hierarchical single-axis
    # stages pass, the scalar loss/overflow collectives are exempt
    big = jnp.ones((256, 64), jnp.float32)
    axes = {"dcn": 2, "data": 4}
    flat = lint_trace.flat_dcn_collective_hazards(
        lambda g: lax.psum(g, ("dcn", "data"))
        + lax.pmax(jnp.sum(g), ("dcn", "data")), big, axes=axes)
    assert flat["hazard"] and flat["flat_collectives"] == 1, flat
    assert flat["findings"][0]["rule"] == "flat-dcn-collective", flat
    assert flat["census"]["other"].get("pmax") == 1, flat
    staged = lint_trace.flat_dcn_collective_hazards(
        lambda g: hierarchy.hier_psum(g, "dcn", "data"), big, axes=axes)
    assert not staged["hazard"], staged
    assert staged["census"]["staged"], staged

    # the canonical pod program (hierarchical ZeRO apply, int8 DCN wire)
    verdict = lint_audit.run_audit(programs=("pod",))
    assert verdict["all_ok"], verdict
    trip = verdict["programs"]["pod"]["tripwires"]["flat-dcn-collective"]
    assert not trip["hazard"], trip
    return {"ok": True, "dcn_source": spec["source"],
            "dcn_s": an["dcn_s"],
            "flat_rule": flat["findings"][0]["rule"]}


def run() -> dict:
    """In-process smoke (no platform mutation — safe under any backend)."""
    results = {}
    for name, fn in (("journal", _check_journal),
                     ("flight", _check_flight),
                     ("health", _check_health),
                     ("watchdog", _check_watchdog),
                     ("hbm", _check_hbm),
                     ("comms", _check_comms),
                     ("mfu", _check_mfu),
                     ("diagnose", _check_diagnose),
                     ("report", _check_report),
                     ("ledger", _check_ledger),
                     ("lint", _check_lint),
                     ("audit", _check_audit),
                     ("plan", _check_plan),
                     ("pod", _check_pod),
                     ("tracing", _check_tracing),
                     ("serve", _check_serve),
                     ("reqtrace", _check_reqtrace)):
        try:
            results[name] = fn()
        except Exception as e:  # noqa: BLE001 - report, don't crash the gate
            results[name] = {"ok": False, "error": f"{type(e).__name__}: "
                                                   f"{str(e)[:300]}"}
    results["all_ok"] = all(v.get("ok") for v in results.values()
                            if isinstance(v, dict))
    return results


def main() -> int:
    # standalone runs must stay off any ambient accelerator plugin (the
    # axon tunnel ignores JAX_PLATFORMS env; force in code, CLAUDE.md)
    # and need the 8-device virtual CPU mesh for the audit check's
    # canonical step programs (same env shaping as lint.audit's main)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - backend already up: run on it
        pass
    results = run()
    print(json.dumps({"monitor_selftest": results}))
    return 0 if results["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
