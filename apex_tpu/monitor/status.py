"""Live run view: ``python -m apex_tpu.monitor.status <run.jsonl>``.

The report CLI judges a FINISHED journal; this one watches a LIVE run —
it tails the journal (and optionally the structured heartbeat + flight
dump next to it) into a one-screen refresh: step cadence and
throughput, loss, loss-scale, HBM curve, pipeline bubble / overlap
stamps, serve queue + SLO attainment + the worst in-flight request
(age, phase, slot — the engine's ``worst_request`` step stamp), the
last hang-attribution
breadcrumb, and the recent alert feed (``monitor/health.py`` rules
replayed over the tail, plus any ``kind="alert"`` rows an armed monitor
journaled live).

Modes:

- default: redraw every ``--interval`` seconds until interrupted (ANSI
  clear; a dumb terminal still gets sequential frames);
- ``--once``: one frame, then exit;
- ``--format json`` (with or without ``--once``): one strict-JSON
  object per frame — the machine consumer's view, parity with
  ``monitor.report --format json``.

Pure host-side stdlib over ``MetricsJournal.read`` (crash-tolerant: a
torn tail renders its good prefix), so it runs anywhere, including
beside a live run appending to the same file (O_APPEND discipline).

No reference-file citation: like the rest of apex_tpu.monitor, NVIDIA
Apex has no telemetry layer; this is the operator console veScale-style
production visibility asks for (PAPERS.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Sequence


def _last(vals: List[Any]) -> Optional[Any]:
    return vals[-1] if vals else None


def snapshot(
    records: Sequence[Dict[str, Any]],
    *,
    heartbeat_path: Optional[str] = None,
    flight_path: Optional[str] = None,
    tail: int = 64,
    max_alerts: int = 8,
) -> Dict[str, Any]:
    """One status frame from a journal's records (+ optional heartbeat/
    flight files). All fields best-effort: a young run shows what it
    has."""
    steps = [r for r in records if r.get("kind") == "step"]
    recent = steps[-tail:]
    out: Dict[str, Any] = {
        "ts": round(time.time(), 3),
        "records": len(records),
        "step_records": len(steps),
        "truncated": bool(getattr(records, "truncated", False)),
    }
    meta = next((r for r in records if r.get("kind") == "meta"), None)
    if meta and meta.get("run"):
        out["run"] = meta["run"]
    if meta and meta.get("fingerprint"):
        out["fingerprint"] = meta["fingerprint"]
        git = (meta.get("env") or {}).get("git")
        if git:
            out["git"] = git
    if recent:
        last = recent[-1]
        out["last_step"] = last.get("step", last.get("window"))
        out["loss"] = last.get("loss")
        out["loss_scale"] = last.get("loss_scale")
        out["overflows"] = last.get("overflows")
        rates = [r["tokens_per_sec"] for r in recent
                 if isinstance(r.get("tokens_per_sec"), (int, float))]
        if rates:
            out["tokens_per_sec"] = round(rates[-1], 1)
        ts = [r["ts"] for r in recent
              if isinstance(r.get("ts"), (int, float))]
        if len(ts) >= 2 and ts[-1] > ts[0]:
            out["steps_per_sec"] = round((len(ts) - 1) / (ts[-1] - ts[0]), 3)
        if isinstance(ts and ts[-1], (int, float)):
            out["last_step_age_s"] = round(time.time() - ts[-1], 1)
        for key in ("bubble_fraction", "overlap_fraction", "queue_depth",
                    "slot_occupancy", "accepted_len", "mfu"):
            vals = [r[key] for r in recent
                    if isinstance(r.get(key), (int, float))]
            if vals:
                out[key] = vals[-1]
        # worst in-flight request (ISSUE 17): the newest decode tick's
        # oldest request — {id, age_s, phase, slot}, stamped by the serve
        # engine only while requests are in flight
        worst = [r["worst_request"] for r in recent
                 if isinstance(r.get("worst_request"), dict)]
        if worst:
            out["worst_request"] = worst[-1]
    # HBM: newest sample from step sub-dicts or standalone hbm rows
    hbm = []
    for r in records:
        if r.get("kind") == "hbm" and isinstance(
                r.get("live_bytes"), (int, float)):
            hbm.append(r["live_bytes"])
        elif isinstance(r.get("hbm"), dict) and isinstance(
                r["hbm"].get("live_bytes"), (int, float)):
            hbm.append(r["hbm"]["live_bytes"])
    if hbm:
        out["hbm"] = {"live_bytes": int(hbm[-1]),
                      "growth_bytes": int(hbm[-1] - hbm[0]),
                      "samples": len(hbm)}
    # serve SLO: the newest window record
    slo = _last([r for r in records if r.get("kind") == "slo"])
    if slo:
        out["slo"] = {k: slo.get(k) for k in
                      ("window", "attainment", "target",
                       "goodput_tokens_per_sec") if slo.get(k) is not None}
    # alert feed: derived over the journal + journaled live rows
    try:
        from apex_tpu.monitor import health as health_mod

        derived = health_mod.scan(records)
    except Exception:  # noqa: BLE001 - status must survive a bad journal
        derived = []
    journaled = [r for r in records if r.get("kind") == "alert"]
    out["alerts"] = {
        "count": len(derived), "journaled": len(journaled),
        "recent": [{k: a.get(k) for k in ("rule", "step", "message")}
                   for a in derived[-max_alerts:]],
    }
    # hang attribution: the structured heartbeat's last breadcrumb
    if heartbeat_path:
        try:
            from apex_tpu.monitor.watchdog import Heartbeat

            hb = Heartbeat.read(heartbeat_path)
        except Exception:  # noqa: BLE001
            hb = None
        if hb:
            out["heartbeat"] = {
                "age_s": (round(time.time() - hb["ts"], 1)
                          if isinstance(hb.get("ts"), (int, float))
                          else None),
                "stage": hb.get("stage"),
                "last_op": (hb.get("last_op") or {}).get("op")
                if isinstance(hb.get("last_op"), dict) else None,
            }
    if flight_path and os.path.exists(flight_path):
        try:
            from apex_tpu.monitor import flight as flight_mod

            dumpd = flight_mod.load(flight_path)
        except Exception:  # noqa: BLE001
            dumpd = None
        if dumpd:
            out["flight"] = {"reason": dumpd.get("reason"),
                             "ts": dumpd.get("ts"),
                             "last_op": (dumpd.get("last_op") or {}).get("op")
                             if isinstance(dumpd.get("last_op"), dict)
                             else None}
    return out


def render(snap: Dict[str, Any], file=None) -> None:
    file = file or sys.stdout
    p = lambda *a: print(*a, file=file)  # noqa: E731
    head = f"run: {snap.get('run', '?')}  records: {snap['records']}"
    if snap.get("fingerprint"):
        head += f"  fingerprint {snap['fingerprint']}"
    if snap.get("git"):
        head += f"  git {snap['git']}"
    if snap.get("truncated"):
        head += "  [TRUNCATED TAIL]"
    p(head)
    parts = []
    if snap.get("last_step") is not None:
        parts.append(f"step {snap['last_step']}")
    if snap.get("loss") is not None:
        parts.append(f"loss {snap['loss']:.4f}")
    if snap.get("loss_scale") is not None:
        parts.append(f"scale {snap['loss_scale']:.0f}")
    if snap.get("tokens_per_sec") is not None:
        parts.append(f"{snap['tokens_per_sec']} tok/s")
    if snap.get("steps_per_sec") is not None:
        parts.append(f"{snap['steps_per_sec']} step/s")
    if snap.get("last_step_age_s") is not None:
        parts.append(f"last step {snap['last_step_age_s']}s ago")
    if parts:
        p("train: " + "  ".join(parts))
    hbm = snap.get("hbm")
    if hbm:
        p(f"hbm: {hbm['live_bytes'] / 1e6:.1f} MB live "
          f"({hbm['growth_bytes'] / 1e6:+.1f} MB over "
          f"{hbm['samples']} samples)")
    tl = [f"{k.split('_')[0]} {snap[k]}" for k in
          ("bubble_fraction", "overlap_fraction") if snap.get(k) is not None]
    if tl:
        p("timeline: " + "  ".join(tl))
    sv = [f"queue {snap['queue_depth']}" if snap.get("queue_depth")
          is not None else None,
          f"occupancy {snap['slot_occupancy']}"
          if snap.get("slot_occupancy") is not None else None,
          f"accepted {snap['accepted_len']}"
          if snap.get("accepted_len") is not None else None]
    sv = [s for s in sv if s]
    slo = snap.get("slo")
    if slo:
        sv.append(f"slo attainment {slo.get('attainment')}"
                  + (f"/{slo['target']}" if slo.get("target") is not None
                     else ""))
        if slo.get("goodput_tokens_per_sec") is not None:
            sv.append(f"goodput {slo['goodput_tokens_per_sec']} tok/s")
    wr = snap.get("worst_request")
    if isinstance(wr, dict):
        sv.append(f"worst req {wr.get('id')} "
                  f"({wr.get('phase')}, slot {wr.get('slot')}, "
                  f"{wr.get('age_s')}s old)")
    if sv:
        p("serve: " + "  ".join(sv))
    hb = snap.get("heartbeat")
    if hb:
        p(f"heartbeat: {hb.get('age_s')}s old  stage {hb.get('stage')!r}"
          + (f"  last op {hb['last_op']}" if hb.get("last_op") else ""))
    fl = snap.get("flight")
    if fl:
        p(f"FLIGHT DUMP: {fl.get('reason')}"
          + (f" (last op {fl['last_op']})" if fl.get("last_op") else ""))
    al = snap["alerts"]
    p(f"alerts: {al['count']}"
      + (f" ({al['journaled']} journaled live)" if al["journaled"] else ""))
    for a in al["recent"]:
        p(f"  [{a['rule']}] step {a.get('step')}: {a.get('message')}")


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.monitor.status",
        description="Tail a MetricsJournal (+ heartbeat/flight files) "
                    "into a one-screen live view.")
    p.add_argument("journal")
    p.add_argument("--heartbeat", default=None, metavar="PATH",
                   help="structured heartbeat file (monitor/watchdog.py) "
                        "— shows age, stage, and the last breadcrumb")
    p.add_argument("--flight", default=None, metavar="PATH",
                   help="flight-dump path to watch (default: "
                        "<journal>.flight.json)")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="json: one strict-JSON object per frame "
                        "(machine consumers)")
    p.add_argument("--tail", type=int, default=64,
                   help="step records in the rolling window")
    args = p.parse_args(list(sys.argv[1:] if argv is None else argv))
    flight_path = args.flight or (args.journal + ".flight.json")

    def frame() -> Dict[str, Any]:
        from apex_tpu.monitor.journal import MetricsJournal

        try:
            records = MetricsJournal.read(args.journal)
        except OSError:
            records = []
        return snapshot(records, heartbeat_path=args.heartbeat,
                        flight_path=flight_path, tail=args.tail)

    while True:
        snap = frame()
        if args.format == "json":
            print(json.dumps(snap, default=str, allow_nan=False))
        else:
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            render(snap)
        if args.once:
            return 0
        sys.stdout.flush()
        try:
            time.sleep(max(args.interval, 0.2))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
