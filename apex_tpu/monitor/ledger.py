"""Run ledger: one append-only record per completed run, across runs.

``report compare`` is strictly pairwise and a journal dies with its run;
nothing tracked run N against runs 1..N-1 and nothing joined the repo's
*predictions* (static-hbm peak bytes, comm census, analytic bubble
floors, pyprof FLOPs) with its *measurements* (journal → ``report``).
The ledger is that longitudinal layer: a JSON-lines file sharing the
journal's strict-JSON / truncated-read semantics, one ``kind="run"``
record per completed run carrying

- ``fingerprint`` + ``config``: the canonicalized parallelism knobs
  (dp/tp/sp/pp/vpp/schedule/zero_level/prefetch/reduce_dtype/moe axis/
  serve knobs) hashed so trajectories group by config, not by path;
- ``env``: provenance (git rev, jax/python versions, device platform,
  the ``APEX_TPU_PEAK_*`` / calibration overrides in force);
- ``measured``: ``report.analyze``'s single-journal rollup (the same
  JSON object ``report --format json`` emits);
- ``predicted``: the off-TPU block from the existing static passes
  (per-step FLOPs/bytes, static comm bytes, analytic bubble floor,
  static-hbm peak estimate, and the modeled step seconds those imply
  under the current peak spec).

CLI: ``python -m apex_tpu.monitor.ledger {list,trend,regress,calibrate}``.
``trend`` renders per-fingerprint trajectories; ``regress`` is the N-run
generalization of ``report compare`` — the newest record gates against
the median of its fingerprint's history through the SAME
``must_not_drop``/``must_not_grow`` predicates, emits the same machine
shape as ``report compare --format json``, and exits non-zero on
regression; ``calibrate`` joins predicted vs measured per record
(``monitor/calibrate.py``) and fits the effective peak constants
``mfu.peak_spec``/``tracing.ici_spec`` consume.

Harness wiring: ``pretrain_gpt/pretrain_bert/generate_gpt --ledger``,
``BENCH_LEDGER``/``APEX_TPU_LEDGER`` env, one row per ``gpt_scaling``
config. Appends are single ``O_APPEND`` writes (concurrent harnesses
interleave whole lines, the journal's shared-file discipline); disarmed
programs are untouched.

No reference-file citation: NVIDIA Apex has no run-tracking layer; this
generalizes the repo's own journal/report discipline across runs
(ROADMAP items 1-3 read from it).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from apex_tpu.monitor.journal import (
    JournalRecords,
    MetricsJournal,
    _sanitize_nonfinite,
    _to_host,
)

ENV_LEDGER = "APEX_TPU_LEDGER"

SCHEMA_VERSION = 1

#: shared crash-tolerant reader: a ledger torn by a kill must still parse
read = MetricsJournal.read


# ---------------------------------------------------------------------------
# fingerprint + environment provenance
# ---------------------------------------------------------------------------


def _canonical(v: Any) -> Any:
    """Canonicalize a config tree: sorted keys, ``None`` values dropped
    (an omitted knob and an explicit None are the same config), scalars
    kept, everything else stringified."""
    if isinstance(v, dict):
        return {str(k): _canonical(x) for k, x in sorted(v.items())
                if x is not None}
    if isinstance(v, (list, tuple)):
        return [_canonical(x) for x in v]
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def config_fingerprint(config: Optional[Dict[str, Any]]) -> str:
    """Stable 12-hex-char fingerprint of a config dict. Same knobs →
    same fingerprint regardless of key order or None-vs-omitted; any
    parallelism knob flip → a new fingerprint (tests pin both)."""
    blob = json.dumps(_canonical(config or {}), sort_keys=True,
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


_ENV_STAMP: Optional[Dict[str, Any]] = None

#: env knobs whose values are provenance (a run measured under an
#: env-overridden peak spec must not trend against a datasheet one)
_PEAK_ENV_KEYS = ("APEX_TPU_PEAK_FLOPS", "APEX_TPU_PEAK_HBM_GBPS",
                  "APEX_TPU_PEAK_ICI_GBPS", "APEX_TPU_CALIBRATION")


def environment_stamp() -> Dict[str, Any]:
    """Provenance stamp: git rev, jax/python versions, device platform,
    peak-spec overrides in force. Cached per process (the git subprocess
    runs once); every field is best-effort — a stamp must never fail a
    run or a journal open."""
    global _ENV_STAMP
    if _ENV_STAMP is not None:
        return dict(_ENV_STAMP)
    stamp: Dict[str, Any] = {
        "python": ".".join(map(str, sys.version_info[:3])),
    }
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=5)
        if out.returncode == 0 and out.stdout.strip():
            stamp["git"] = out.stdout.strip()
    except Exception:  # noqa: BLE001 - no git is fine
        pass
    try:
        import jax

        stamp["jax"] = jax.__version__
        devs = jax.devices()
        stamp["device_count"] = len(devs)
        stamp["device_platform"] = (
            f"{devs[0].platform} "
            f"{getattr(devs[0], 'device_kind', '') or ''}").strip()
    except Exception:  # noqa: BLE001 - no backend: stay host-side
        pass
    overrides = {k: os.environ[k] for k in _PEAK_ENV_KEYS
                 if os.environ.get(k)}
    if overrides:
        stamp["peak_overrides"] = overrides
    _ENV_STAMP = stamp
    return dict(stamp)


# ---------------------------------------------------------------------------
# append
# ---------------------------------------------------------------------------


def append(path: str, record: Dict[str, Any]) -> Dict[str, Any]:
    """Append one record as a single ``O_APPEND`` write (whole lines
    interleave under concurrent writers — the journal's shared-file
    semantics). Values sanitize to strict JSON exactly like journal
    lines (non-finite floats → null + ``nonfinite_keys``)."""
    rec = {"v": SCHEMA_VERSION, "kind": record.get("kind", "run"),
           "ts": round(time.time(), 3)}
    for k, v in record.items():
        rec[k] = _to_host(v)
    bad: List[str] = []
    rec = _sanitize_nonfinite(rec, "", bad)
    if bad:
        rec["nonfinite_keys"] = bad
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    data = (json.dumps(rec, default=str, allow_nan=False) + "\n").encode()
    fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
    try:
        os.write(fd, data)
    finally:
        os.close(fd)
    return rec


def _measured_block(records: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """The measured side: ``report.analyze``'s rollup, trimmed for a
    per-run record (short lists; the journal keeps the long form)."""
    from apex_tpu.monitor import report

    out = report.analyze(records, max_list=5)
    # provenance rides the ledger record's own config/env blocks; the
    # journal meta would duplicate it per run
    out.pop("meta", None)
    return out


def _finish_predicted(pred: Dict[str, Any]) -> Dict[str, Any]:
    """Derive the modeled step seconds (cost-model compute + wire-model
    comm under the CURRENT peak spec) from whatever static pieces the
    caller provided, stamping the spec provenance so a calibrated re-read
    is distinguishable from a datasheet one."""
    flops = pred.get("flops_per_step")
    comm = pred.get("comm_bytes_per_step")
    if not (isinstance(flops, (int, float)) and flops > 0) and not (
            isinstance(comm, (int, float)) and comm > 0):
        return pred
    if isinstance(pred.get("modeled_step_s"), (int, float)):
        # a caller-provided model (the planner's bubble/overlap-aware
        # step seconds, pretrain_gpt --plan auto) outranks the simple
        # no-overlap sum here — don't overwrite it
        return pred
    try:
        from apex_tpu.monitor import mfu as _mfu
        from apex_tpu.monitor import tracing as _tracing

        spec = _mfu.peak_spec()
        ici = _tracing.ici_spec()
        compute_s = (flops / spec["peak_flops"]
                     if isinstance(flops, (int, float)) and flops > 0
                     else 0.0)
        comm_s = (comm / ici["ici_bytes_per_sec"]
                  if isinstance(comm, (int, float)) and comm > 0 else 0.0)
        # the no-overlap model: an upper bound a well-overlapped step
        # beats (wall_ratio < 1), a stalled one misses (wall_ratio > 1)
        pred["modeled_step_s"] = round(compute_s + comm_s, 6)
        pred["spec"] = {
            "peak_flops": spec["peak_flops"],
            "peak_flops_source": spec["source"],
            "ici_bytes_per_sec": ici["ici_bytes_per_sec"],
            "ici_source": ici["source"],
        }
    except Exception:  # noqa: BLE001 - prediction is best-effort
        pass
    return pred


def append_run(
    path: str,
    *,
    run: str,
    config: Optional[Dict[str, Any]] = None,
    journal: Optional[str] = None,
    records: Optional[Sequence[Dict[str, Any]]] = None,
    measured: Optional[Dict[str, Any]] = None,
    predicted: Optional[Dict[str, Any]] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The one-call harness hook: read the run's journal (when given),
    roll it up into the measured block, finish the predicted block, and
    append one fingerprinted ``kind="run"`` record.

    ``predicted`` carries whatever static pieces the harness computed at
    arm time — ``flops_per_step``/``bytes_per_step`` (pyprof trace),
    ``comm_bytes_per_step`` (comm census), ``hbm_peak_bytes``
    (static-hbm pass), ``bubble_floor`` (analytic) — missing pieces are
    salvaged from the journal's own armed stamps. ``measured`` overrides
    the journal rollup for harnesses that journal nothing (a minimal
    ``{"tokens_per_sec": {"p50": ...}}``-shaped dict).
    """
    if records is None and journal:
        try:
            records = read(journal)
        except OSError:
            records = None
    if measured is None:
        measured = _measured_block(records) if records else {}
    pred = dict(predicted or {})
    if records:
        steps = [r for r in records if r.get("kind") == "step"]
        if "bubble_floor" not in pred:
            floor = next((r["bubble_fraction_expected"] for r in steps
                          if isinstance(r.get("bubble_fraction_expected"),
                                        (int, float))), None)
            if floor is not None:
                pred["bubble_floor"] = floor
    pred = _finish_predicted(pred)
    canon = _canonical(config or {})
    rec = {
        "kind": "run",
        "run": run,
        "fingerprint": config_fingerprint(config),
        "config": canon,
        "env": environment_stamp(),
        "measured": measured,
        "predicted": pred,
    }
    if extra:
        rec.update(extra)
    return append(path, rec)


def append_scaling_row(path: str, row: Dict[str, Any],
                       *, run: str = "gpt_scaling") -> Optional[Dict[str, Any]]:
    """One ledger record per ``benchmarks/gpt_scaling.py`` config row:
    the row's measurements become the measured block, its static
    census/floor the predicted block. Skipped rows return None."""
    if "skipped" in row or row.get("config", {}).get("placement_rung"):
        return None
    measured: Dict[str, Any] = {"step_records": 1}
    if isinstance(row.get("tokens_per_sec"), (int, float)):
        measured["tokens_per_sec"] = {"p50": row["tokens_per_sec"]}
    if isinstance(row.get("avg_iteration_time_s"), (int, float)):
        measured["wall_s"] = {"p50": row["avg_iteration_time_s"]}
    if isinstance(row.get("loss"), (int, float)):
        measured["loss"] = {"last": row["loss"]}
    for key in ("comm_bytes_by_axis", "comm_bytes_by_verb_dtype"):
        if isinstance(row.get(key), dict):
            measured[key] = row[key]
    mfu = row.get("mfu") or {}
    if isinstance(mfu.get("mfu"), (int, float)):
        measured["mfu"] = {"p50": mfu["mfu"],
                           "peak_source": mfu.get("peak_source")}
    alerts = row.get("alerts")
    if isinstance(alerts, dict) and "count" in alerts:
        measured["alerts"] = alerts
    tl = row.get("timeline") or {}
    anatomy = tl.get("anatomy") or {}
    tl_out: Dict[str, Any] = {}
    if isinstance(anatomy.get("overlap_fraction"), (int, float)):
        tl_out["overlap_fraction"] = {"p50": anatomy["overlap_fraction"]}
    if tl_out:
        measured["timeline"] = tl_out
    pred: Dict[str, Any] = {}
    if isinstance(tl.get("expected_bubble_fraction"), (int, float)) \
            and tl["expected_bubble_fraction"] > 0:
        pred["bubble_floor"] = tl["expected_bubble_fraction"]
    wall = row.get("avg_iteration_time_s")
    tflops = mfu.get("achieved_tflops")
    if isinstance(tflops, (int, float)) and isinstance(wall, (int, float)):
        pred["flops_per_step"] = round(tflops * 1e12 * wall, 1)
    comm_total = 0.0
    for axis_row in (row.get("comm_bytes_by_axis") or {}).values():
        if isinstance(axis_row, dict):
            comm_total += float(axis_row.get("bytes", 0))
    if comm_total:
        pred["comm_bytes_per_step"] = comm_total
    return append_run(path, run=run, config=row.get("config"),
                      measured=measured, predicted=pred)


# ---------------------------------------------------------------------------
# trend / regress
# ---------------------------------------------------------------------------


def _dig(d: Dict[str, Any], path: Tuple[str, ...]) -> Optional[float]:
    cur: Any = d
    for key in path:
        if not isinstance(cur, dict):
            return None
        cur = cur.get(key)
    return cur if isinstance(cur, (int, float)) else None


#: the trended/gated metric surface: (name, path into the measured
#: block, direction, absolute slack). Directions reuse report's shared
#: predicate pair; slacks match ``report.compare``'s per-check choices.
GATES: Tuple[Tuple[str, Tuple[str, ...], str, float], ...] = (
    ("tokens_per_sec_p50", ("tokens_per_sec", "p50"), "drop", 0.0),
    ("wall_s_p50", ("wall_s", "p50"), "grow", 0.0),
    ("hbm_peak_bytes", ("hbm", "peak_bytes"), "grow", float(64 << 20)),
    ("bubble_fraction_p50", ("timeline", "bubble_fraction", "p50"),
     "grow", 0.01),
    ("overlap_fraction_p50", ("timeline", "overlap_fraction", "p50"),
     "drop", 0.0),
    ("opt_state_bytes_last", ("opt_state_bytes", "last"), "grow", 0.0),
    ("param_bytes_last", ("param_bytes", "last"), "grow", 0.0),
    ("ttft_ms_p50", ("serving", "ttft_ms", "p50"), "grow", 0.05),
    ("itl_ms_p50", ("serving", "itl_ms", "p50"), "grow", 0.05),
    ("itl_ms_p99", ("serving", "itl_ms", "p99"), "grow", 0.5),
    ("tokens_per_sec_per_user_p50",
     ("serving", "tokens_per_sec_per_user", "p50"), "drop", 0.0),
    ("prefix_hit_rate", ("serving", "prefix_hit_rate"), "drop", 0.0),
    ("accepted_len_p50", ("serving", "accepted_len", "p50"), "drop", 0.0),
    ("slo_attainment_p50", ("slo", "attainment", "p50"), "drop", 0.0),
    # ISSUE 17: TTFT/ITL attribution drift — the queue share of each
    # latency class must not grow across runs (report.compare's
    # queue-fraction gate, pointed at the ledger history)
    ("ttft_queue_frac",
     ("serving", "attribution", "ttft", "queue_frac"), "grow", 0.05),
    ("itl_queue_frac",
     ("serving", "attribution", "itl", "queue_frac"), "grow", 0.05),
)


def _runs(records: Sequence[Dict[str, Any]],
          fingerprint: Optional[str] = None) -> List[Dict[str, Any]]:
    out = [r for r in records if r.get("kind") == "run"]
    if fingerprint:
        out = [r for r in out if str(r.get("fingerprint", "")
                                     ).startswith(fingerprint)]
    return out


def _metric_row(rec: Dict[str, Any]) -> Dict[str, Any]:
    measured = rec.get("measured") or {}
    row: Dict[str, Any] = {"ts": rec.get("ts"), "run": rec.get("run"),
                           "step_records": measured.get("step_records")}
    for name, path, _, _ in GATES:
        v = _dig(measured, path)
        if v is not None:
            row[name] = v
    loss = _dig(measured, ("loss", "last"))
    if loss is not None:
        row["loss_last"] = loss
    alerts = _dig(measured, ("alerts", "count"))
    if alerts is not None:
        row["alerts"] = alerts
    return row


def trend(records: Sequence[Dict[str, Any]],
          fingerprint: Optional[str] = None) -> Dict[str, Any]:
    """Per-fingerprint trajectories: for each config fingerprint, the
    metric rows of its runs in append order — the across-runs view
    ``report`` cannot give (it sees one journal at a time)."""
    out: Dict[str, Any] = {}
    for rec in _runs(records, fingerprint):
        fp = str(rec.get("fingerprint"))
        slot = out.setdefault(fp, {"config": rec.get("config"), "rows": []})
        slot["rows"].append(_metric_row(rec))
    return out


def regress(
    records: Sequence[Dict[str, Any]],
    *,
    fingerprint: Optional[str] = None,
    threshold: float = 0.05,
    window: int = 8,
    max_alerts: Optional[int] = None,
) -> Dict[str, Any]:
    """Gate the newest run record against its fingerprint's history.

    The N-run generalization of ``report.compare``: the baseline for
    each metric is the MEDIAN over the previous ``window`` runs of the
    same fingerprint (a single noisy predecessor can't poison the gate),
    and every check reuses the shared ``must_not_drop``/``must_not_grow``
    predicates. Emits the same machine shape as
    ``report compare --format json`` (``checks``/``regressed``/``ok``).
    A first run has no history: every check skips and the verdict is ok
    (self-history always passes).
    """
    from apex_tpu.monitor.diagnose import median as _median
    from apex_tpu.monitor.report import must_not_drop, must_not_grow

    runs = _runs(records, fingerprint)
    if not runs:
        return {"threshold": threshold, "checks": [], "regressed": [],
                "ok": True, "a": {"runs": 0}, "b": {},
                "note": "no run records"
                + (f" for fingerprint {fingerprint}" if fingerprint else "")}
    cand = runs[-1]
    history = runs[:-1][-window:]
    cand_row = _metric_row(cand)
    hist_rows = [_metric_row(r) for r in history]
    checks: List[Dict[str, Any]] = []

    def check(name, va, vb, *, worse):
        if va is None or vb is None:
            return
        checks.append({"check": name, "a": va, "b": vb,
                       "regressed": bool(worse(va, vb))})

    def baseline(name):
        vals = [r[name] for r in hist_rows if isinstance(
            r.get(name), (int, float))]
        return _median(vals) if vals else None

    if history:
        # structural gate first (report.compare's discipline): a run that
        # journaled nothing must FAIL against a history that did
        check("step_records", baseline("step_records"),
              cand_row.get("step_records", 0),
              worse=lambda va, vb: va > 0 and vb == 0)
        for name, _, direction, slack in GATES:
            pred = (must_not_drop(threshold) if direction == "drop"
                    else must_not_grow(threshold, slack=slack))
            check(name, baseline(name), cand_row.get(name), worse=pred)
        if max_alerts is not None:
            check("alerts", baseline("alerts") or 0,
                  cand_row.get("alerts", 0),
                  worse=lambda va, vb: vb > max(va, max_alerts))
    regressed = [c["check"] for c in checks if c["regressed"]]
    return {"threshold": threshold, "checks": checks,
            "regressed": regressed, "ok": not regressed,
            "a": {"runs": len(history),
                  "fingerprint": str(cand.get("fingerprint"))},
            "b": {"ts": cand.get("ts"), "run": cand.get("run"),
                  "step_records": cand_row.get("step_records")}}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.monitor.ledger",
        description="Run-ledger analysis: per-config trajectories, the "
                    "N-run regression gate, and cost-model calibration.")
    sub = p.add_subparsers(dest="cmd", required=True)

    lp = sub.add_parser("list", help="one line per run record")
    lp.add_argument("ledger")
    lp.add_argument("--format", choices=("text", "json"), default="text")

    tp = sub.add_parser("trend", help="per-fingerprint trajectories")
    tp.add_argument("ledger")
    tp.add_argument("--fingerprint", default=None,
                    help="restrict to one config fingerprint (prefix ok)")
    tp.add_argument("--format", choices=("text", "json"), default="text")

    rp = sub.add_parser(
        "regress",
        help="gate the newest run against its fingerprint's history "
             "(exit 1 on regression; report compare's machine shape)")
    rp.add_argument("ledger")
    rp.add_argument("--fingerprint", default=None)
    rp.add_argument("--threshold", type=float, default=0.05)
    rp.add_argument("--window", type=int, default=8,
                    help="history depth the baseline medians over")
    rp.add_argument("--max-alerts", type=int, default=None,
                    help="arm the health-alert gate (report compare "
                         "--max-alerts semantics)")
    rp.add_argument("--format", choices=("text", "json"), default="text")

    cp = sub.add_parser(
        "calibrate",
        help="join predicted vs measured per record and fit the "
             "effective peak constants (monitor/calibrate.py)")
    cp.add_argument("ledger")
    cp.add_argument("--output", default=None, metavar="PATH",
                    help="write the fitted calibration file (arm it via "
                         "APEX_TPU_CALIBRATION=<PATH>; the file then "
                         "outranks the APEX_TPU_PEAK_* env overrides)")
    cp.add_argument("--format", choices=("text", "json"), default="text")

    args = p.parse_args(list(sys.argv[1:] if argv is None else argv))
    try:
        records = read(args.ledger)
    except OSError:
        records = JournalRecords()

    if args.cmd == "list":
        runs = _runs(records)
        if args.format == "json":
            print(json.dumps([_metric_row(r) | {
                "fingerprint": str(r.get("fingerprint"))} for r in runs]))
        else:
            for r in runs:
                row = _metric_row(r)
                parts = [f"{str(r.get('fingerprint')):<12}",
                         f"{str(r.get('run')):<14}"]
                for key in ("tokens_per_sec_p50", "wall_s_p50",
                            "loss_last", "hbm_peak_bytes"):
                    if key in row:
                        parts.append(f"{key}={_fmt(row[key])}")
                print("  ".join(parts))
            print(f"{len(runs)} run record(s)"
                  + (", TRUNCATED final line"
                     if getattr(records, "truncated", False) else ""))
        return 0

    if args.cmd == "trend":
        tr = trend(records, args.fingerprint)
        if args.format == "json":
            print(json.dumps(tr))
        else:
            for fp, slot in tr.items():
                cfg = json.dumps(slot["config"], sort_keys=True)
                print(f"fingerprint {fp} ({len(slot['rows'])} run(s)) "
                      f"{cfg}")
                for row in slot["rows"]:
                    parts = [f"  ts={row.get('ts')}"]
                    for key in ("tokens_per_sec_p50", "wall_s_p50",
                                "loss_last", "bubble_fraction_p50",
                                "overlap_fraction_p50", "hbm_peak_bytes",
                                "ttft_ms_p50", "itl_ms_p50", "alerts"):
                        if key in row:
                            parts.append(f"{key}={_fmt(row[key])}")
                    print("  ".join(parts))
            if not tr:
                print("no run records")
        return 0

    if args.cmd == "regress":
        res = regress(records, fingerprint=args.fingerprint,
                      threshold=args.threshold, window=args.window,
                      max_alerts=args.max_alerts)
        if args.format == "json":
            print(json.dumps(res))
        else:
            for c in res["checks"]:
                mark = "REGRESSED" if c["regressed"] else "ok"
                print(f"{c['check']:<28} hist={_fmt(c['a'])} "
                      f"new={_fmt(c['b'])}  {mark}")
            if res.get("note"):
                print(res["note"])
            print("REGRESSION: " + ", ".join(res["regressed"])
                  if res["regressed"] else
                  f"no regression ({res['a']['runs'] if 'runs' in res['a'] else 0} "
                  f"history run(s))")
        return 0 if res["ok"] else 1

    if args.cmd == "calibrate":
        from apex_tpu.monitor import calibrate as cal_mod

        out = {"joins": cal_mod.summarize(records),
               "fit": cal_mod.fit(records)}
        if args.output:
            out["calibration_file"] = cal_mod.save(args.output, out["fit"])
        if args.format == "json":
            print(json.dumps(out))
        else:
            for fp, row in out["joins"].items():
                parts = [f"fingerprint {fp} ({row['records']} run(s))"]
                for key in ("hbm_ratio", "bubble_ratio", "comm_ratio",
                            "wall_ratio"):
                    if key in row:
                        parts.append(f"{key}={row[key]}")
                print("  ".join(parts))
            fit = out["fit"]
            parts = []
            if "peak_flops" in fit:
                parts.append(f"peak_flops={fit['peak_flops']:.4g}")
            if "peak_ici_bytes_per_sec" in fit:
                parts.append("peak_ici_gbps="
                             f"{fit['peak_ici_bytes_per_sec'] / 1e9:.4g}")
            if "peak_hbm_bytes_per_sec" in fit:
                parts.append("peak_hbm_gbps="
                             f"{fit['peak_hbm_bytes_per_sec'] / 1e9:.4g}")
            print("fit: " + (" ".join(parts) if parts
                             else "not enough signal"))
            if args.output:
                print(f"calibration file: {out['calibration_file']} "
                      f"(arm via APEX_TPU_CALIBRATION)")
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
