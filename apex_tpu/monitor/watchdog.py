"""Library-grade watchdog: checkpoint + heartbeat protocol for wedged runs.

Extracted from bench.py's watchdog parent (its ``_watchdog``/``checkpoint``
pair): the r5 tunnel sessions showed a failure regime no in-process wrapper
can catch — the device tunnel WEDGES and a device call simply never returns
(a 4096x4096 matmul probe sat 10+ minutes; no OOM, no exception). Any
long-lived process that owns evidence (a bench round, a training run with
an in-memory metrics journal) must therefore run as a CHILD of a watchdog
that can kill the whole process tree and surface the child's last durable
state.

Protocol (two small files, both written by the child):

- **checkpoint file** (path in ``$APEX_TPU_CHECKPOINT_PATH``): a JSON
  record the child overwrites after every completed stage — the "what we
  know so far" the parent recovers when the child dies or hangs.
- **heartbeat file** (path in ``$APEX_TPU_HEARTBEAT_PATH``): a tiny JSON
  ``{"ts", "stage"}`` the child touches via :class:`Heartbeat` whenever it
  makes progress. With ``stall_timeout`` set, the parent kills a child
  whose heartbeat goes stale long before the hard deadline — distinguishing
  "wedged" from "slow but alive" (a retry-heavy but HEALTHY round must not
  be killed mid-stage; bench.py's deadline comment).

The parent (:func:`run_under_watchdog`) spawns the child in its own session
so a kill takes the WHOLE tree — the wedged device call usually lives in a
grandchild, which a bare ``proc.kill()`` would orphan, leaving it pinning
the chip.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional


class Heartbeat:
    """Child-side progress beacon (one JSON object, atomically replaced)."""

    ENV = "APEX_TPU_HEARTBEAT_PATH"

    def __init__(self, path: str):
        self.path = path

    @classmethod
    def from_env(cls, var: Optional[str] = None) -> Optional["Heartbeat"]:
        path = os.environ.get(var or cls.ENV)
        return cls(path) if path else None

    def beat(self, stage: str = "", record: Optional[Dict[str, Any]] = None):
        """Record progress; never raises (telemetry must not kill work —
        non-serializable record values stringify via ``default=str``)."""
        payload = {"ts": time.time(), "stage": stage}
        if record is not None:
            payload["record"] = record
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, self.path)
        except Exception:  # noqa: BLE001 - see docstring
            try:
                os.unlink(tmp)
            except OSError:
                pass

    @staticmethod
    def read(path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


class WatchdogResult(NamedTuple):
    """Outcome of one supervised child run.

    ``status``: ``"ok"`` (child exited by itself — inspect ``returncode``),
    ``"deadline"`` (hard budget exceeded, tree killed), or ``"stalled"``
    (heartbeat went stale past ``stall_timeout``, tree killed).
    ``record`` is the child's last checkpoint (None if never written);
    ``heartbeat`` its last beat. ``stdout`` is everything the child printed.
    """

    status: str
    returncode: Optional[int]
    stdout: str
    record: Optional[Dict[str, Any]]
    heartbeat: Optional[Dict[str, Any]]
    reason: str


def _kill_tree(proc: subprocess.Popen):
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except OSError:
        proc.kill()
    proc.wait()


def run_under_watchdog(
    cmd: List[str],
    *,
    deadline: float,
    stall_timeout: Optional[float] = None,
    checkpoint_env: str = "APEX_TPU_CHECKPOINT_PATH",
    heartbeat_env: str = Heartbeat.ENV,
    env: Optional[Dict[str, str]] = None,
    poll_s: float = 0.25,
) -> WatchdogResult:
    """Run ``cmd`` under a hard deadline + optional heartbeat stall check.

    The child finds its checkpoint/heartbeat paths in ``checkpoint_env`` /
    ``heartbeat_env``; anything it durably wrote there survives a kill and
    comes back in the result. stdout is drained on a thread (a full pipe
    must not wedge the child — that would be the watchdog inventing the
    failure mode it guards against); stderr passes through to the parent's.
    """
    fd, ckpt = tempfile.mkstemp(prefix="apex_tpu_ckpt_", suffix=".json")
    os.close(fd)
    os.unlink(ckpt)  # child creates it on first checkpoint
    fd, hb_path = tempfile.mkstemp(prefix="apex_tpu_hb_", suffix=".json")
    os.close(fd)
    os.unlink(hb_path)
    child_env = dict(os.environ if env is None else env)
    child_env[checkpoint_env] = ckpt
    child_env[heartbeat_env] = hb_path

    start = time.time()
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                            env=child_env, start_new_session=True)
    chunks: List[str] = []

    def _drain():
        try:
            for line in proc.stdout:
                chunks.append(line)
        except ValueError:
            pass  # stream closed under us at kill time

    reader = threading.Thread(target=_drain, daemon=True)
    reader.start()

    status, reason = "ok", ""
    try:
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            now = time.time()
            if now - start > deadline:
                status = "deadline"
                reason = (f"deadline {deadline:g}s exceeded "
                          "(wedged tunnel?)")
                _kill_tree(proc)
                break
            if stall_timeout is not None:
                hb = Heartbeat.read(hb_path)
                last = hb["ts"] if hb and "ts" in hb else start
                if now - last > stall_timeout:
                    stage = (hb or {}).get("stage", "<no beat yet>")
                    status = "stalled"
                    reason = (f"no heartbeat for {stall_timeout:g}s "
                              f"(last stage: {stage})")
                    _kill_tree(proc)
                    break
            time.sleep(poll_s)
        reader.join(timeout=5)
        return WatchdogResult(
            status=status,
            returncode=proc.returncode,
            stdout="".join(chunks),
            record=Heartbeat.read(ckpt),
            heartbeat=Heartbeat.read(hb_path),
            reason=reason,
        )
    finally:
        for path in (ckpt, hb_path):
            try:
                os.unlink(path)
            except OSError:
                pass


def checkpoint_path(var: str = "APEX_TPU_CHECKPOINT_PATH") -> Optional[str]:
    """Child-side accessor for the checkpoint file path (None when not
    running under a watchdog)."""
    return os.environ.get(var)


def write_checkpoint(record: Dict[str, Any],
                     var: str = "APEX_TPU_CHECKPOINT_PATH") -> bool:
    """Child-side: persist the partial record; no-op without a watchdog.

    Atomic (tmp + rename): a parent that kills this process mid-write must
    never recover a truncated JSON; non-serializable values stringify."""
    path = checkpoint_path(var)
    if not path:
        return False
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(record, f, default=str)
        os.replace(tmp, path)
        return True
    except Exception:  # noqa: BLE001 - checkpointing must not kill work
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


if __name__ == "__main__":  # tiny manual harness: watchdog a shell command
    rc_cmd = sys.argv[1:] or [sys.executable, "-c", "print('hello')"]
    res = run_under_watchdog(rc_cmd, deadline=60, stall_timeout=None)
    print(json.dumps({"status": res.status, "rc": res.returncode,
                      "reason": res.reason}))
