"""Library-grade watchdog: checkpoint + heartbeat protocol for wedged runs.

Extracted from bench.py's watchdog parent (its ``_watchdog``/``checkpoint``
pair): the r5 tunnel sessions showed a failure regime no in-process wrapper
can catch — the device tunnel WEDGES and a device call simply never returns
(a 4096x4096 matmul probe sat 10+ minutes; no OOM, no exception). Any
long-lived process that owns evidence (a bench round, a training run with
an in-memory metrics journal) must therefore run as a CHILD of a watchdog
that can kill the whole process tree and surface the child's last durable
state.

Protocol (two small files, both written by the child):

- **checkpoint file** (path in ``$APEX_TPU_CHECKPOINT_PATH``): a JSON
  record the child overwrites after every completed stage — the "what we
  know so far" the parent recovers when the child dies or hangs.
- **heartbeat file** (path in ``$APEX_TPU_HEARTBEAT_PATH``): a structured
  JSON record ``{"ts", "stage", "last_op", "pid", "seq"}`` the child
  touches via :class:`Heartbeat` whenever it makes progress. ``last_op``
  is the latest breadcrumb (``monitor/flight.py``): the ``comm:`` scope
  or device→host fetch the child most recently ENTERED — so with
  ``stall_timeout`` set, the parent's kill report names the last
  operation the child entered before wedging, not just the stage
  checkpoint (hang ATTRIBUTION, not just hang detection; for a compiled
  step wedged on-device that operation is its fetch point — comm-scope
  breadcrumbs fire at trace time and in the eager per-tick drives).
  Reads are journal-style
  tolerant: a torn heartbeat salvages its stage/last-op fields instead of
  raising, so the kill report still names the last breadcrumb.

The parent (:func:`run_under_watchdog`) spawns the child in its own session
so a kill takes the WHOLE tree — the wedged device call usually lives in a
grandchild, which a bare ``proc.kill()`` would orphan, leaving it pinning
the chip. When the child advertised a flight-recorder path
(``flight_env``), a kill also publishes a parent-side flight dump from the
surviving heartbeat + checkpoint (``flight.write_kill_dump``) — SIGKILL
leaves the child's in-memory ring unrecoverable, so the parent writes what
it has.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional

# salvage patterns for torn heartbeat files (tolerant read, below)
_SALVAGE_STAGE = re.compile(r'"stage"\s*:\s*"([^"]*)"')
_SALVAGE_OP = re.compile(r'"op"\s*:\s*"([^"]*)"')


class Heartbeat:
    """Child-side progress beacon (one JSON object, atomically replaced).

    Every beat carries the structured record ``{"ts", "stage", "pid",
    "seq", "last_op"}`` — ``last_op`` is the newest flight-recorder
    breadcrumb (the ``comm:`` scope / fetch point most recently entered,
    ``monitor/flight.py``), so the parent's stall report can attribute
    the hang to an operation, not just a stage."""

    ENV = "APEX_TPU_HEARTBEAT_PATH"

    def __init__(self, path: str):
        self.path = path
        self.seq = 0

    @classmethod
    def from_env(cls, var: Optional[str] = None) -> Optional["Heartbeat"]:
        path = os.environ.get(var or cls.ENV)
        return cls(path) if path else None

    def beat(self, stage: str = "", record: Optional[Dict[str, Any]] = None,
             last_op: Optional[Dict[str, Any]] = None):
        """Record progress; never raises (telemetry must not kill work —
        non-serializable record values stringify via ``default=str``).
        ``last_op`` defaults to the flight recorder's latest breadcrumb."""
        self.seq += 1
        payload: Dict[str, Any] = {"ts": time.time(), "stage": stage,
                                   "pid": os.getpid(), "seq": self.seq}
        try:
            from apex_tpu.monitor import flight as _flight

            if stage:
                _flight.set_stage(stage)
            op = last_op if last_op is not None else _flight.last_op()
            if op is not None:
                payload["last_op"] = op
        except Exception:  # noqa: BLE001 - see docstring
            pass
        if record is not None:
            payload["record"] = record
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, self.path)
        except Exception:  # noqa: BLE001 - see docstring
            try:
                os.unlink(tmp)
            except OSError:
                pass

    @staticmethod
    def read(path: str) -> Optional[Dict[str, Any]]:
        """Journal-style tolerant read: a well-formed heartbeat parses
        whole; a torn/corrupt one salvages its ``stage``/``last_op``
        string fields by pattern (flagged ``"salvaged": true``) so a
        kill report can still name the last breadcrumb; nothing
        recoverable returns None."""
        try:
            with open(path) as f:
                raw = f.read()
        except OSError:
            return None
        try:
            obj = json.loads(raw)
            if isinstance(obj, dict):
                return obj
        except ValueError:
            pass
        out: Dict[str, Any] = {}
        m = _SALVAGE_STAGE.search(raw)
        if m:
            out["stage"] = m.group(1)
        m = _SALVAGE_OP.search(raw)
        if m:
            out["last_op"] = {"op": m.group(1)}
        if not out:
            return None
        out["salvaged"] = True
        return out


class WatchdogResult(NamedTuple):
    """Outcome of one supervised child run.

    ``status``: ``"ok"`` (child exited by itself — inspect ``returncode``),
    ``"deadline"`` (hard budget exceeded, tree killed), or ``"stalled"``
    (heartbeat went stale past ``stall_timeout``, tree killed).
    ``record`` is the child's last checkpoint (None if never written);
    ``heartbeat`` its last beat. ``stdout`` is everything the child printed.
    ``flight`` is the path of the flight dump published for a killed child
    (the child's own, or the parent-side ``write_kill_dump``; None when no
    flight path was in play or the child exited by itself).
    """

    status: str
    returncode: Optional[int]
    stdout: str
    record: Optional[Dict[str, Any]]
    heartbeat: Optional[Dict[str, Any]]
    reason: str
    flight: Optional[str] = None


def _kill_tree(proc: subprocess.Popen):
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except OSError:
        proc.kill()
    proc.wait()


def _attribute(hb: Optional[Dict[str, Any]]) -> str:
    """Render a heartbeat's hang attribution: stage + last breadcrumb."""
    stage = (hb or {}).get("stage") or "<no beat yet>"
    out = f"last stage: {stage}"
    op = (hb or {}).get("last_op")
    if isinstance(op, dict) and op.get("op"):
        out += f"; last op: {op['op']}"
    return out


def run_under_watchdog(
    cmd: List[str],
    *,
    deadline: float,
    stall_timeout: Optional[float] = None,
    checkpoint_env: str = "APEX_TPU_CHECKPOINT_PATH",
    heartbeat_env: str = Heartbeat.ENV,
    env: Optional[Dict[str, str]] = None,
    poll_s: float = 0.25,
    flight_path: Optional[str] = None,
    flight_env: str = "APEX_TPU_FLIGHT",
) -> WatchdogResult:
    """Run ``cmd`` under a hard deadline + optional heartbeat stall check.

    The child finds its checkpoint/heartbeat paths in ``checkpoint_env`` /
    ``heartbeat_env``; anything it durably wrote there survives a kill and
    comes back in the result. stdout is drained on a thread (a full pipe
    must not wedge the child — that would be the watchdog inventing the
    failure mode it guards against); stderr passes through to the parent's.

    A kill's ``reason`` carries the hang ATTRIBUTION from the structured
    heartbeat: the last stage AND the last breadcrumbed operation (the
    ``comm:`` scope or device→host fetch the child entered last). With
    ``flight_path`` set, the child finds it in ``flight_env`` (arming its
    in-process flight recorder lazily) and a kill publishes a parent-side
    dump there when the child could not (``flight.write_kill_dump``).
    """
    fd, ckpt = tempfile.mkstemp(prefix="apex_tpu_ckpt_", suffix=".json")
    os.close(fd)
    os.unlink(ckpt)  # child creates it on first checkpoint
    fd, hb_path = tempfile.mkstemp(prefix="apex_tpu_hb_", suffix=".json")
    os.close(fd)
    os.unlink(hb_path)
    child_env = dict(os.environ if env is None else env)
    child_env[checkpoint_env] = ckpt
    child_env[heartbeat_env] = hb_path
    if flight_path:
        child_env[flight_env] = flight_path

    start = time.time()
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                            env=child_env, start_new_session=True)
    chunks: List[str] = []

    def _drain():
        try:
            for line in proc.stdout:
                chunks.append(line)
        except ValueError:
            pass  # stream closed under us at kill time

    reader = threading.Thread(target=_drain, daemon=True)
    reader.start()

    status, reason = "ok", ""
    try:
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            now = time.time()
            if now - start > deadline:
                status = "deadline"
                reason = (f"deadline {deadline:g}s exceeded "
                          f"(wedged tunnel?; "
                          f"{_attribute(Heartbeat.read(hb_path))})")
                _kill_tree(proc)
                break
            if stall_timeout is not None:
                hb = Heartbeat.read(hb_path)
                last = hb["ts"] if hb and "ts" in hb else start
                if now - last > stall_timeout:
                    status = "stalled"
                    reason = (f"no heartbeat for {stall_timeout:g}s "
                              f"({_attribute(hb)})")
                    _kill_tree(proc)
                    break
            time.sleep(poll_s)
        reader.join(timeout=5)
        flight_out = None
        if flight_path and status != "ok":
            # SIGKILL took the child's in-memory ring with it; publish
            # the parent-side dump from what survived (no-op when the
            # child managed its own dump first — THIS run's file wins,
            # but a stale artifact from a previous run does not)
            try:
                from apex_tpu.monitor import flight as _flight

                _flight.write_kill_dump(
                    flight_path, reason=reason, status=status,
                    heartbeat=Heartbeat.read(hb_path),
                    checkpoint=Heartbeat.read(ckpt),
                    newer_than=start)
                flight_out = flight_path
            except Exception:  # noqa: BLE001 - report must not kill parent
                pass
        elif flight_path and os.path.exists(flight_path):
            try:
                # advertise only a dump the CHILD just wrote — never a
                # leftover from an earlier run at the same path
                if os.path.getmtime(flight_path) >= start:
                    flight_out = flight_path
            except OSError:
                pass
        return WatchdogResult(
            status=status,
            returncode=proc.returncode,
            stdout="".join(chunks),
            record=Heartbeat.read(ckpt),
            heartbeat=Heartbeat.read(hb_path),
            reason=reason,
            flight=flight_out,
        )
    finally:
        for path in (ckpt, hb_path):
            try:
                os.unlink(path)
            except OSError:
                pass


def checkpoint_path(var: str = "APEX_TPU_CHECKPOINT_PATH") -> Optional[str]:
    """Child-side accessor for the checkpoint file path (None when not
    running under a watchdog)."""
    return os.environ.get(var)


def write_checkpoint(record: Dict[str, Any],
                     var: str = "APEX_TPU_CHECKPOINT_PATH") -> bool:
    """Child-side: persist the partial record; no-op without a watchdog.

    Atomic (tmp + rename): a parent that kills this process mid-write must
    never recover a truncated JSON; non-serializable values stringify."""
    path = checkpoint_path(var)
    if not path:
        return False
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(record, f, default=str)
        os.replace(tmp, path)
        return True
    except Exception:  # noqa: BLE001 - checkpointing must not kill work
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


if __name__ == "__main__":  # tiny manual harness: watchdog a shell command
    rc_cmd = sys.argv[1:] or [sys.executable, "-c", "print('hello')"]
    res = run_under_watchdog(rc_cmd, deadline=60, stall_timeout=None)
    print(json.dumps({"status": res.status, "rc": res.returncode,
                      "reason": res.reason}))
