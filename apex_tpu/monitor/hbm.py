"""HBM occupancy monitor: live-array byte curves + lane-padded estimates.

Two r4/r5 regimes motivated this (PERF_NOTES.md, CLAUDE.md gotchas): a
long-lived process accumulates HBM *below* ``jax.live_arrays()`` through the
axon tunnel (a config that OOMs at batch 1 runs fine in a fresh process),
and co-tenant occupation makes placement fail while compute runs fine. Both
were diagnosed postmortem from bench stderr; this module turns them into
sampled curves: what Python CAN see (``jax.live_arrays()`` totals, padded
and unpadded) over time, so the *visible* residency can be subtracted from
an OOM to expose the below-Python remainder.

Padded accounting: TPU HBM layouts tile the two minor dims — minor to the
128-lane vreg width, second-minor to the sublane count for the dtype (8 for
4-byte, 16 for 2-byte, 32 for 1-byte elements). A ``(b, h, sq, 1)`` f32
operand therefore occupies 128x its ``nbytes`` at a custom-call boundary
(2 GB for 16 MB of lse at 512k tokens — the measured tax that forced the
streamed kernels' dense lse tables, ``ops/flash_attention.py``). The same
rule is applied per live array here, as an estimate of placed footprint.

All functions are host-side only: no device syncs, safe to call on the hot
path after a step's loss fetch.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

_NUM_LANES = 128
_SUBLANE_BYTES = 32  # sublanes x itemsize is constant: 8x4 = 16x2 = 32x1


def lane_padded_bytes(shape, itemsize: int) -> int:
    """Bytes of one array under TPU (sublane, lane) tiling.

    Minor dim pads to 128 lanes; second-minor pads to ``32 // itemsize``
    sublanes (f32: 8, bf16: 16, int8: 32). Rank-0/1 arrays are laid out as
    a single (1, n) tile row.
    """
    itemsize = max(int(itemsize), 1)
    dims = [int(d) for d in shape] or [1]
    if len(dims) == 1:
        dims = [1] + dims
    sublanes = max(_SUBLANE_BYTES // itemsize, 1)
    minor = -(-dims[-1] // _NUM_LANES) * _NUM_LANES
    second = -(-dims[-2] // sublanes) * sublanes
    n = minor * second
    for d in dims[:-2]:
        n *= d
    return n * itemsize


def live_array_stats(platform: Optional[str] = None) -> Dict[str, Any]:
    """Snapshot of Python-visible device residency.

    Returns ``{"live_bytes", "padded_bytes", "count", "largest_bytes"}``
    summed over ``jax.live_arrays(platform)``. ``live_bytes`` counts logical
    ``nbytes`` (global, for sharded arrays); ``padded_bytes`` applies the
    lane/sublane tiling estimate per array. Deleted arrays report 0.
    """
    import jax

    live = padded = largest = 0
    count = 0
    try:
        arrays = jax.live_arrays(platform) if platform else jax.live_arrays()
    except Exception:  # noqa: BLE001 - no backend yet
        arrays = []
    for a in arrays:
        try:
            if getattr(a, "is_deleted", lambda: False)():
                continue
            nb = int(a.nbytes)
            pb = lane_padded_bytes(a.shape, a.dtype.itemsize)
        except Exception:  # noqa: BLE001 - tokens/exotic avals
            continue
        live += nb
        padded += pb
        largest = max(largest, nb)
        count += 1
    return {"live_bytes": live, "padded_bytes": padded, "count": count,
            "largest_bytes": largest}


# ---------------------------------------------------------------------------
# Sequence-parallel activation accounting: the tp-x memory claim as a number
# ---------------------------------------------------------------------------

#: the (b, s, h)-shaped tensors a transformer layer materializes OUTSIDE the
#: TP GEMM regions — between a row-parallel reduce (psum or psum_scatter)
#: and the next column-parallel entry. These are exactly the tensors
#: sequence parallelism shrinks by tp: under plain TP they are replicated
#: full-sequence on every TP rank; under ``sequence_parallel=True`` each
#: rank holds its (b, s/tp, h) shard (models/_transformer.py regions).
SEQUENCE_REGION_SITES = (
    "ln1_out",          # LN before attention (input to the qkv column GEMM)
    "attn_dropout_out",  # post-attention dropout output
    "residual1",        # first residual sum
    "ln2_out",          # LN before the MLP
    "mlp_dropout_out",  # post-MLP dropout output
    "residual2",        # second residual sum (the layer's carry)
)


def sequence_region_layer_bytes(
    batch: int,
    seq: int,
    hidden: int,
    *,
    tp: int = 1,
    sequence_parallel: bool = False,
    itemsize: int = 2,
    padded: bool = True,
) -> Dict[str, Any]:
    """Per-layer bytes of the sequence-region activations on ONE TP rank.

    ``sequence_parallel=True`` divides the sequence dim by ``tp`` (the
    reduce-scatter shard); ``padded`` applies :func:`lane_padded_bytes`
    (the T(8,128) layout these tensors occupy when resident). A trace-time
    ESTIMATE of the shape algebra, not a measurement — remat/fusion decide
    which sites are simultaneously live, but every site shrinks by the same
    factor, so the plain/SP ratio is exact.
    """
    s_local = seq // tp if (sequence_parallel and tp > 1) else seq
    shape = (batch, s_local, hidden)
    per_site = (lane_padded_bytes(shape, itemsize) if padded
                else batch * s_local * hidden * itemsize)
    return {
        "shape": list(shape),
        "seq_local": s_local,
        "per_site_bytes": per_site,
        "sites": list(SEQUENCE_REGION_SITES),
        "layer_bytes": per_site * len(SEQUENCE_REGION_SITES),
    }


def sequence_parallel_activation_report(
    batch: int,
    seq: int,
    hidden: int,
    num_layers: int,
    tp: int,
    *,
    itemsize: int = 2,
) -> Dict[str, Any]:
    """Plain-TP vs sequence-parallel per-layer activation bytes, per rank.

    The evidence artifact behind the "every activation in the non-TP
    regions shrinks by tp" claim (benchmarks/overlap_evidence.py,
    PERF_NOTES.md): same shape algebra as the layer regions, reported as
    numbers rather than prose."""
    plain = sequence_region_layer_bytes(
        batch, seq, hidden, tp=tp, sequence_parallel=False,
        itemsize=itemsize)
    sp = sequence_region_layer_bytes(
        batch, seq, hidden, tp=tp, sequence_parallel=True, itemsize=itemsize)
    return {
        "batch": batch, "seq": seq, "hidden": hidden,
        "num_layers": num_layers, "tp": tp, "itemsize": itemsize,
        "sites_per_layer": len(SEQUENCE_REGION_SITES),
        "plain_per_layer_bytes": plain["layer_bytes"],
        "sp_per_layer_bytes": sp["layer_bytes"],
        "plain_total_bytes": plain["layer_bytes"] * num_layers,
        "sp_total_bytes": sp["layer_bytes"] * num_layers,
        "savings_bytes_per_layer":
            plain["layer_bytes"] - sp["layer_bytes"],
        "ratio": round(plain["layer_bytes"] / max(sp["layer_bytes"], 1), 3),
    }


# ---------------------------------------------------------------------------
# Optimizer-state accounting: the ZeRO memory claim as a number
# ---------------------------------------------------------------------------

#: fp32 arrays the O2 optimizer keeps per parameter: master + Adam/LAMB
#: exp_avg + exp_avg_sq (amp/frontend.py MPOptState + FusedAdamState)
OPTIMIZER_STATE_COPIES = 3


def optimizer_state_report(
    params: Any,
    dp: int,
    *,
    state_copies: int = OPTIMIZER_STATE_COPIES,
    itemsize: int = 4,
) -> Dict[str, Any]:
    """Replicated vs ZeRO-sharded optimizer-state bytes on ONE rank.

    ``params`` is any pytree with shaped leaves (arrays or
    ShapeDtypeStructs — e.g. ``jax.eval_shape(model.init, key)`` for the
    345M flagship shape without touching HBM). Replicated: every rank
    holds ``state_copies`` fp32 arrays per param, lane-padded in the
    param's own shape. ZeRO over ``dp`` ranks
    (``amp.MixedPrecisionOptimizer(zero_axis=...)``): every rank holds
    ``state_copies`` 1-D fp32 chunks of ``ceil(size/dp)`` elements — 1-D
    chunks tile as a single (1, n) row, so the padded footprint is also
    ~1/dp. Same shape-algebra-as-evidence discipline as
    :func:`sequence_parallel_activation_report`."""
    import jax

    from apex_tpu.optimizers.distributed import chunk_size

    # a ZeRO chunk is a large CONTIGUOUS flat buffer resident in HBM, not
    # a (1, n) operand row at a custom-call boundary: model it as packed
    # linear storage rounded up to whole (sublanes x 128-lane) tile
    # granules — the (1, n) single-row rule (lane_padded_bytes on rank-1)
    # would book an 8x sublane tax that a multi-MB flat vector does not pay
    sublanes = max(_SUBLANE_BYTES // max(int(itemsize), 1), 1)
    granule = sublanes * _NUM_LANES

    repl = repl_padded = zero = zero_padded = 0
    count = n_leaves = 0
    for leaf in jax.tree.leaves(params):
        shape = tuple(int(d) for d in getattr(leaf, "shape", ()) or ())
        size = 1
        for d in shape:
            size *= d
        k = chunk_size(size, dp)
        repl += size * itemsize
        repl_padded += lane_padded_bytes(shape, itemsize)
        zero += k * itemsize
        zero_padded += -(-k // granule) * granule * itemsize
        count += size
        n_leaves += 1
    return {
        "dp": dp, "param_count": count, "param_leaves": n_leaves,
        "state_copies": state_copies, "itemsize": itemsize,
        "replicated_bytes_per_rank": repl * state_copies,
        "replicated_padded_bytes_per_rank": repl_padded * state_copies,
        "zero_bytes_per_rank": zero * state_copies,
        "zero_padded_bytes_per_rank": zero_padded * state_copies,
        "savings_bytes_per_rank": (repl - zero) * state_copies,
        "ratio": round(repl / max(zero, 1), 3),
    }


def param_state_report(
    params: Any,
    dp: int,
    *,
    state_copies: int = OPTIMIZER_STATE_COPIES,
    master_itemsize: int = 4,
) -> Dict[str, Any]:
    """Replicated vs ZeRO-1/2 vs ZeRO-3 per-rank param+master+moment bytes.

    Extends :func:`optimizer_state_report` to the WORKING params — the last
    replicated O(model) tensor ZeRO-3 removes. ``params`` is any pytree
    with shaped leaves (arrays or ShapeDtypeStructs, e.g.
    ``jax.eval_shape(model.init, key)`` cast to the compute policy, so each
    leaf's own dtype prices the working copy — bf16 under O2). Columns,
    all per rank:

    - ``replicated``  — full working params + ``state_copies`` full fp32
      arrays per param (no ZeRO);
    - ``zero12``      — full working params + fp32 state as 1-D
      ``ceil(size/dp)`` chunks (PR-5 ``zero_axis=...``: one
      implementation, masters and moments always shard together, so
      ZeRO-1 and ZeRO-2 price identically here);
    - ``zero3``       — working params AND fp32 state as chunks
      (``zero_level=3``: the bf16 model persists 1/dp, each layer
      all-gathered just-in-time inside the layer loop — the transient
      gather working set is O(1 layer), not priced as residency).

    Chunks are priced as packed linear storage rounded to whole tile
    granules (the :func:`optimizer_state_report` rule).
    """
    import jax
    import numpy as np

    from apex_tpu.optimizers.distributed import chunk_size

    def tile_granule(itemsize):
        sublanes = max(_SUBLANE_BYTES // max(int(itemsize), 1), 1)
        return sublanes * _NUM_LANES

    granule = tile_granule(master_itemsize)

    p_full = p_full_padded = p_chunk = 0
    o_full = o_full_padded = o_chunk = 0
    count = n_leaves = 0
    for leaf in jax.tree.leaves(params):
        shape = tuple(int(d) for d in getattr(leaf, "shape", ()) or ())
        try:
            itemsize = int(np.dtype(leaf.dtype).itemsize)
        except Exception:  # noqa: BLE001 - dtype-less leaves price as bf16
            itemsize = 2
        size = 1
        for d in shape:
            size *= d
        k = chunk_size(size, dp)
        # working chunks round to the granule of THEIR dtype (bf16: 2048
        # elems), masters/moments to the fp32 granule
        p_granule = tile_granule(itemsize)
        p_full += size * itemsize
        p_full_padded += lane_padded_bytes(shape, itemsize)
        p_chunk += -(-k // p_granule) * p_granule * itemsize
        o_full += size * master_itemsize
        o_full_padded += lane_padded_bytes(shape, master_itemsize)
        o_chunk += -(-k // granule) * granule * master_itemsize
        count += size
        n_leaves += 1
    o_full *= state_copies
    o_full_padded *= state_copies
    o_chunk *= state_copies
    table = {
        "replicated": {"param_bytes": p_full, "opt_bytes": o_full,
                       "total_bytes": p_full + o_full},
        "zero12": {"param_bytes": p_full, "opt_bytes": o_chunk,
                   "total_bytes": p_full + o_chunk},
        "zero3": {"param_bytes": p_chunk, "opt_bytes": o_chunk,
                  "total_bytes": p_chunk + o_chunk},
    }
    return {
        "dp": dp, "param_count": count, "param_leaves": n_leaves,
        "state_copies": state_copies, "master_itemsize": master_itemsize,
        "per_rank": table,
        "replicated_padded_param_bytes": p_full_padded,
        "param_ratio": round(p_full / max(p_chunk, 1), 3),
        "total_ratio": round((p_full + o_full)
                             / max(p_chunk + o_chunk, 1), 3),
    }


def opt_state_bytes(opt_state: Any) -> int:
    """Per-rank bytes of a (possibly sharded) optimizer-state pytree.

    For committed global arrays the first addressable shard's bytes ARE
    the per-device footprint — a replicated leaf's shard is the full
    array, a ZeRO chunk leaf's shard is 1/n of it — so the same call
    reports the honest per-rank number either way. Host-side only; used
    to arm ``MetricsJournal.set_opt_state_bytes``.
    """
    import jax

    total = 0
    for leaf in jax.tree.leaves(opt_state):
        try:
            shards = getattr(leaf, "addressable_shards", None)
            if shards:
                total += int(shards[0].data.nbytes)
            else:
                total += int(leaf.nbytes)
        except Exception:  # noqa: BLE001 - abstract/exotic leaves
            continue
    return total


def param_bytes(params: Any) -> int:
    """Per-rank bytes of a (possibly chunk-sharded) working-param pytree —
    the same addressable-shard accounting as :func:`opt_state_bytes`: a
    replicated leaf books its full array, a ZeRO-3 chunk leaf its 1/n
    shard. Host-side only; arms ``MetricsJournal.set_param_bytes``."""
    return opt_state_bytes(params)


class HBMMonitor:
    """Sampling monitor over :func:`live_array_stats`.

    >>> mon = HBMMonitor(journal=journal)   # journal optional
    >>> mon.sample("before")                # establishes the baseline
    >>> ...training...
    >>> mon.sample("after")
    >>> mon.growth_bytes()                  # retained-leak detector

    ``growth_bytes`` is last-sample minus baseline ``live_bytes``: a loop
    that retains arrays (or exception tracebacks pinning device buffers —
    the bench.py OOM-ladder trap) shows monotone growth; a healthy loop is
    flat. The below-Python regime is the complement: an OOM whose ladder
    rung exceeds HBM while ``growth_bytes`` stays ~0 means the occupation
    is NOT Python-visible (fresh-process territory, bench.py stage 0).
    """

    def __init__(self, journal=None, label: str = ""):
        self.journal = journal
        self.label = label
        self.samples = []

    def sample(self, tag: str = "") -> Dict[str, Any]:
        stats = live_array_stats()
        stats["tag"] = tag
        self.samples.append(stats)
        if self.journal is not None:
            self.journal.log(dict(stats, kind="hbm", label=self.label))
        return stats

    @property
    def baseline(self) -> Optional[Dict[str, Any]]:
        return self.samples[0] if self.samples else None

    def growth_bytes(self) -> int:
        """Python-visible residency growth since the first sample."""
        if len(self.samples) < 2:
            return 0
        return self.samples[-1]["live_bytes"] - self.samples[0]["live_bytes"]

    def peak_bytes(self) -> int:
        return max((s["live_bytes"] for s in self.samples), default=0)
