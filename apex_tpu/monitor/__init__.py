"""apex_tpu.monitor — runtime telemetry: journal, HBM, comms, watchdog.

The framework's flagship evidence (PERF_NOTES.md) was produced by
instrumentation hand-rolled inside ``bench.py``: per-stage checkpoints, a
watchdog parent for the wedged-tunnel regime, OOM-ladder narration, and
throughput windows timed with the device→host-fetch convention. This package
extracts those patterns into a reusable subsystem any training loop
(``bench.py``, ``examples/``, ``benchmarks/gpt_scaling.py``) can attach:

- :mod:`journal` — :class:`MetricsJournal`: per-step JSON-lines records
  (wall time, tokens/s, loss, global grad-norm, loss-scale state, cumulative
  overflow counts) with rank info, honoring the tunnel timing discipline
  (the clock stops on a device→host fetch, never bare ``block_until_ready``).
- :mod:`hbm` — :class:`HBMMonitor`: ``jax.live_arrays()`` byte totals plus
  lane-padded residency estimates (the T(8,128) layout tax documented in
  ``ops/flash_attention.py``), so below-Python HBM accumulation and co-tenant
  occupation become visible curves instead of postmortems.
- :mod:`comms` — named scopes + byte counters for the collective verbs in
  ``parallel/collectives.py`` and ``transformer/tensor_parallel/mappings.py``;
  ``pyprof`` trace-joins then attribute measured comm seconds per mesh axis,
  and :func:`comms.comm_accounting` tallies algorithmic bytes at trace time.
- :mod:`watchdog` — the library-grade extraction of bench.py's watchdog
  parent: a checkpoint-file + heartbeat-file protocol so any long-lived
  process survives the wedged-tunnel regime (device calls that never return)
  with its last per-stage record intact.
- :mod:`mfu` — MFU/roofline reporting: joins pyprof cost totals (FLOPs +
  bytes) with journal wall times against a per-platform peak-spec table
  (env-overridable for the tunnel chip) into ``mfu`` / ``hbm_bw_util`` /
  compute-vs-memory-bound fields per journal window.
- :mod:`diagnose` — :class:`OverflowForensics` (on ``found_inf`` or a
  loss spike, dump per-parameter-group grad norms, loss-scale history,
  and the cumulative-overflow trajectory, so the first non-finite layer
  is attributable from the journal alone) and :class:`RecompileTracker`
  (jit cache misses + compile seconds per argument-shape signature —
  the shape-churn detector).
- :mod:`tracing` — :class:`Tracer`: nested named host-side spans
  (per-rank, crash-tolerant JSON-lines mirroring the journal) plus the
  timeline analyzers: measured pipeline bubble fraction vs the analytic
  :func:`tracing.expected_bubble_fraction` floor, comm/compute
  :func:`tracing.step_anatomy` (fractions sum to 1.0 per window), and
  Chrome trace-event export for ``chrome://tracing`` / Perfetto.
- :mod:`report` — ``python -m apex_tpu.monitor.report <run.jsonl>``:
  throughput percentiles, stall gaps, loss spikes, HBM-growth trend,
  per-rank straggler skew, comm rollups; ``... report compare A B``
  exits non-zero on regression (the bench-trajectory machine gate).
- :mod:`flight` — :class:`FlightRecorder` (ISSUE 14): a bounded
  in-memory ring of recent journal/span records + breadcrumbs, dumped as
  one strict-JSON crash file (``<journal>.flight.json``) on unhandled
  exception, SIGTERM, or watchdog kill — with an HBM snapshot and the
  last loss-scale state; breadcrumbs at the ``comm:`` scopes and
  device→host fetch points feed the structured heartbeat, so a watchdog
  kill report names the operation the child was stuck in.
- :mod:`health` — :class:`HealthMonitor` (ISSUE 14): streaming
  per-record detectors (loss spike, grad-norm drift, tok/s collapse,
  HBM growth, overflow rate, serve queue/SLO burn) evaluated as records
  are written, emitting ``kind="alert"`` rows; ``health.scan`` replays
  them offline for ``report``'s alerts section and the
  ``report compare --max-alerts`` gate.
- :mod:`status` — ``python -m apex_tpu.monitor.status <run.jsonl>``:
  live one-screen tail of a running journal (+ heartbeat/flight files):
  step rate, loss, HBM, bubble/overlap, serve queue + SLO, the last
  breadcrumb, and the alert feed; ``--once --format json`` for machines.
- :mod:`ledger` — ``python -m apex_tpu.monitor.ledger`` (ISSUE 16): an
  append-only run ledger — one fingerprinted record per completed run
  (config + environment stamp + measured ``report`` rollup + the
  predicted block from the static passes); ``trend`` renders
  per-fingerprint trajectories, ``regress`` gates the newest run against
  its fingerprint's history through the shared predicates (the N-run
  generalization of ``report compare``).
- :mod:`calibrate` — predicted-vs-measured joins per ledger record
  (hbm/bubble/comm/wall error ratios) and the fitted effective
  peak-FLOPs / peak-ICI constants; an armed ``APEX_TPU_CALIBRATION``
  file outranks the ``APEX_TPU_PEAK_*`` env overrides in
  ``mfu.peak_spec`` / ``tracing.ici_spec``.
- :mod:`selftest` — ``python -m apex_tpu.monitor.selftest``: fast off-TPU
  smoke of all pieces, wired into ``__graft_entry__.dryrun_multichip``.

No reference-file citation: the reference (NVIDIA Apex) has no runtime
telemetry layer; this subsystem generalizes bench.py's measurement
discipline (bench.py module docstring, PERF_NOTES.md).
"""

from apex_tpu.monitor.comms import (  # noqa: F401
    CommAccount,
    collective_scope,
    comm_accounting,
)
from apex_tpu.monitor.diagnose import (  # noqa: F401
    OverflowForensics,
    RecompileTracker,
    group_grad_norms,
)
from apex_tpu.monitor.hbm import (  # noqa: F401
    HBMMonitor,
    lane_padded_bytes,
    live_array_stats,
    sequence_parallel_activation_report,
    sequence_region_layer_bytes,
)
from apex_tpu.monitor.journal import (  # noqa: F401
    JournalRecords,
    MetricsJournal,
    scaler_state,
)
from apex_tpu.monitor.tracing import (  # noqa: F401
    Tracer,
    chrome_trace,
    expected_bubble_fraction,
    pipeline_anatomy,
    step_anatomy,
    timeline_summary,
)
from apex_tpu.monitor.mfu import (  # noqa: F401
    compiled_step_costs,
    mfu_metrics,
    peak_spec,
    traced_step_costs,
)
from apex_tpu.monitor.watchdog import (  # noqa: F401
    Heartbeat,
    WatchdogResult,
    run_under_watchdog,
)
from apex_tpu.monitor.flight import (  # noqa: F401
    FlightRecorder,
    breadcrumb,
)
from apex_tpu.monitor.health import (  # noqa: F401
    HealthMonitor,
)

# ledger/calibrate/report/status/selftest are deliberately NOT imported
# here: they are `python -m apex_tpu.monitor.<name>` CLI entry points and
# importing them in the package init trips runpy's double-import warning
