"""Online health rules: streaming per-record detectors → ``alert`` rows.

``monitor.report`` judges a run after it ends; this module judges it
WHILE it runs. A :class:`HealthMonitor` consumes journal records as they
are written (wire one into ``MetricsJournal(health=...)`` — ``log``
feeds every record through and appends the resulting ``kind="alert"``
rows to the same journal) and fires bounded, de-stormed alerts:

- ``loss-spike``        — |loss| beyond ``spike_factor`` × the trailing
  median (THE shared predicate, ``diagnose.is_loss_spike`` — online,
  offline report, and forensics can never desynchronize);
- ``grad-norm-drift``   — grad norm beyond ``drift_factor`` × its
  trailing median (the pre-divergence tell);
- ``throughput-collapse`` — tokens/s below ``collapse_frac`` × the
  trailing median (co-tenant pressure / silent recompile churn);
- ``hbm-growth``        — live-array bytes more than ``hbm_slack_bytes``
  above the first sample (the below-Python-leak curve, re-armed one
  slack past each firing so a creeping leak keeps alerting);
- ``overflow-rate``     — cumulative overflow skips above
  ``overflow_rate_max`` of steps (latched once);
- ``queue-depth``       — serve queue depth above ``queue_limit`` for
  ``queue_consecutive`` ticks (off until a limit is configured);
- ``slo-burn``          — a serve SLO window record (``kind="slo"``,
  emitted by ``serve.Engine`` when targets are set) whose attainment
  fell below its own stamped target.

:func:`scan` replays the same rules over a stored journal — the offline
twin ``report.analyze`` uses for its alerts section and ``report compare
--max-alerts`` gates on, so the gate works on journals that never armed
a monitor. Pure host-side stdlib: compiled step/serve programs are
untouched (the byte-identity discipline of ``--trace``).

No reference-file citation: NVIDIA Apex has no telemetry layer; the
SLO-burn framing follows production serving practice (veScale's
operational-visibility thesis, PAPERS.md).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from apex_tpu.monitor.diagnose import is_loss_spike, median

#: every rule this module can fire (docs + report rollup keys)
RULES = ("loss-spike", "grad-norm-drift", "throughput-collapse",
         "hbm-growth", "overflow-rate", "queue-depth", "slo-burn")

_DEFAULTS = dict(
    spike_factor=3.0, spike_window=16,
    drift_factor=10.0, drift_window=16, drift_min_history=8,
    collapse_frac=0.5, collapse_window=16, collapse_min_history=8,
    hbm_slack_bytes=256 << 20,
    overflow_rate_max=0.1, overflow_min_steps=20,
    queue_limit=None, queue_consecutive=8,
    slo_attainment_min=None,   # None: honor each slo record's own target
    cooldown=8,                # records suppressed per rule after a fire
)


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) else None


class HealthMonitor:
    """Streaming rule evaluator. Feed records via :meth:`observe`; it
    returns the ``kind="alert"`` rows this record triggered (usually
    empty). Holds all trailing-window state; one instance per run.

    >>> journal = MetricsJournal(path, health=HealthMonitor())
    >>> ...  # step_end/log as usual; alerts land in the journal
    >>> journal.health.alerts     # everything fired so far
    """

    def __init__(self, **cfg):
        unknown = set(cfg) - set(_DEFAULTS)
        if unknown:
            raise TypeError(f"unknown health config keys: {sorted(unknown)}")
        self.cfg = dict(_DEFAULTS, **cfg)
        self.alerts: List[Dict[str, Any]] = []
        c = self.cfg
        self._losses: deque = deque(maxlen=int(c["spike_window"]))
        self._grads: deque = deque(maxlen=int(c["drift_window"]))
        self._rates: deque = deque(maxlen=int(c["collapse_window"]))
        self._hbm_first: Optional[float] = None
        self._hbm_next_fire: Optional[float] = None
        self._overflow_latched = False
        self._queue_over = 0
        self._steps = 0
        self._since_fire: Dict[str, int] = {}

    # -- de-storming --------------------------------------------------------
    def _fire(self, rule: str, *, step=None, value=None, baseline=None,
              message: str = "") -> Optional[Dict[str, Any]]:
        """Emit one alert unless the rule is inside its cooldown window
        (a sustained condition must page once per window, not once per
        record)."""
        if self._since_fire.get(rule, 1 << 30) < int(self.cfg["cooldown"]):
            return None
        self._since_fire[rule] = 0
        alert: Dict[str, Any] = {"kind": "alert", "rule": rule,
                                 "message": message}
        if step is not None:
            alert["step"] = step
        if value is not None:
            alert["value"] = round(float(value), 6)
        if baseline is not None:
            alert["baseline"] = round(float(baseline), 6)
        self.alerts.append(alert)
        return alert

    # -- the streaming entry point ------------------------------------------
    def observe(self, rec: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Evaluate one journal record; returns the alerts it fired."""
        if not isinstance(rec, dict) or rec.get("kind") == "alert":
            return []
        for rule in self._since_fire:
            self._since_fire[rule] += 1
        out: List[Dict[str, Any]] = []
        kind = rec.get("kind", "step")
        if kind == "step":
            out.extend(self._observe_step(rec))
        if kind == "hbm" or isinstance(rec.get("hbm"), dict):
            out.extend(self._observe_hbm(rec))
        if kind == "slo":
            out.extend(self._observe_slo(rec))
        return out

    # -- training-shaped rules ----------------------------------------------
    def _observe_step(self, rec: Dict[str, Any]) -> List[Dict[str, Any]]:
        c = self.cfg
        out: List[Dict[str, Any]] = []
        step = rec.get("step", rec.get("window"))
        self._steps += 1

        # loss spike — overflow steps never enter the baseline or the
        # spike check, and sanitized-NaN losses are the forensics
        # layer's business (matching report.analyze exactly)
        loss = _num(rec.get("loss"))
        keys = rec.get("nonfinite_keys") or []
        loss_is_nan = any(k == "loss" or k.endswith(".loss") for k in keys)
        if loss is not None and not rec.get("found_inf") and not loss_is_nan:
            base = (median(self._losses)
                    if len(self._losses) >= 4 else None)
            if is_loss_spike(loss, base, c["spike_factor"]):
                a = self._fire("loss-spike", step=step, value=loss,
                               baseline=base,
                               message=f"loss {loss:.4g} > "
                                       f"{c['spike_factor']:g}x trailing "
                                       f"median {base:.4g}")
                if a:
                    out.append(a)
            self._losses.append(loss)

        # grad-norm drift
        gn = _num(rec.get("grad_norm"))
        if gn is not None and not rec.get("found_inf"):
            base = (median(self._grads)
                    if len(self._grads) >= int(c["drift_min_history"])
                    else None)
            if base is not None and gn > c["drift_factor"] * max(base, 1e-12):
                a = self._fire("grad-norm-drift", step=step, value=gn,
                               baseline=base,
                               message=f"grad norm {gn:.4g} > "
                                       f"{c['drift_factor']:g}x trailing "
                                       f"median {base:.4g}")
                if a:
                    out.append(a)
            self._grads.append(gn)

        # throughput collapse
        rate = _num(rec.get("tokens_per_sec"))
        if rate is not None:
            base = (median(self._rates)
                    if len(self._rates) >= int(c["collapse_min_history"])
                    else None)
            if base is not None and rate < c["collapse_frac"] * base:
                a = self._fire("throughput-collapse", step=step, value=rate,
                               baseline=base,
                               message=f"tokens/s {rate:.4g} < "
                                       f"{c['collapse_frac']:g}x trailing "
                                       f"median {base:.4g}")
                if a:
                    out.append(a)
            self._rates.append(rate)

        # overflow rate (cumulative counter rides every step record)
        ov = _num(rec.get("overflows"))
        if (ov is not None and not self._overflow_latched
                and self._steps >= int(c["overflow_min_steps"])):
            rate_ov = ov / self._steps
            if rate_ov > c["overflow_rate_max"]:
                self._overflow_latched = True
                a = self._fire("overflow-rate", step=step, value=rate_ov,
                               baseline=c["overflow_rate_max"],
                               message=f"overflow rate {rate_ov:.3f} over "
                                       f"{self._steps} steps exceeds "
                                       f"{c['overflow_rate_max']:g}")
                if a:
                    out.append(a)

        # serve queue depth (only when a limit is configured)
        qd = _num(rec.get("queue_depth"))
        if qd is not None and c["queue_limit"] is not None:
            if qd > c["queue_limit"]:
                self._queue_over += 1
                if self._queue_over >= int(c["queue_consecutive"]):
                    a = self._fire("queue-depth", step=step, value=qd,
                                   baseline=c["queue_limit"],
                                   message=f"queue depth {qd:g} above "
                                           f"{c['queue_limit']:g} for "
                                           f"{self._queue_over} tick(s)")
                    if a:
                        out.append(a)
            else:
                self._queue_over = 0
        return out

    def _observe_hbm(self, rec: Dict[str, Any]) -> List[Dict[str, Any]]:
        c = self.cfg
        sub = rec.get("hbm") if isinstance(rec.get("hbm"), dict) else rec
        live = _num(sub.get("live_bytes"))
        if live is None:
            return []
        if self._hbm_first is None:
            self._hbm_first = live
            self._hbm_next_fire = live + float(c["hbm_slack_bytes"])
            return []
        if live > self._hbm_next_fire:
            # re-arm one slack past this firing: a creeping leak keeps
            # alerting instead of latching silent after the first page
            self._hbm_next_fire = live + float(c["hbm_slack_bytes"])
            a = self._fire(
                "hbm-growth", step=rec.get("step"), value=live,
                baseline=self._hbm_first,
                message=f"live bytes grew "
                        f"{(live - self._hbm_first) / 1e6:.1f} MB past the "
                        f"{c['hbm_slack_bytes'] / 1e6:.0f} MB slack")
            return [a] if a else []
        return []

    def _observe_slo(self, rec: Dict[str, Any]) -> List[Dict[str, Any]]:
        c = self.cfg
        att = _num(rec.get("attainment"))
        target = (c["slo_attainment_min"]
                  if c["slo_attainment_min"] is not None
                  else _num(rec.get("target")))
        if att is None or target is None or att >= target:
            return []
        # ISSUE 17: the engine stamps the window's dominant latency phase
        # on its slo records — name it, so the alert says WHERE the burn
        # came from ("slo-burn: queue-dominated")
        dom = rec.get("dominant_phase")
        prefix = f"{dom}-dominated: " if isinstance(dom, str) and dom else ""
        a = self._fire("slo-burn", step=rec.get("window"), value=att,
                       baseline=target,
                       message=f"{prefix}SLO attainment {att:.3f} below "
                               f"target {target:.3f} this window")
        return [a] if a else []

    def summary(self) -> Dict[str, Any]:
        return summarize(self.alerts)


def summarize(alerts: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """``{"count", "by_rule"}`` rollup of an alert list — THE one copy
    shared by :meth:`HealthMonitor.summary`, ``report.analyze``'s alerts
    section, and the gpt_scaling per-config stamp."""
    by_rule: Dict[str, int] = {}
    for a in alerts:
        by_rule[a["rule"]] = by_rule.get(a["rule"], 0) + 1
    return {"count": len(alerts), "by_rule": by_rule}


def scan(records: Sequence[Dict[str, Any]], **cfg) -> List[Dict[str, Any]]:
    """Replay the streaming rules over a stored journal — the offline
    twin of a wired :class:`HealthMonitor` (same rule objects, so online
    and offline verdicts can never drift). Journaled ``kind="alert"``
    rows are skipped on input (no feedback)."""
    mon = HealthMonitor(**cfg)
    out: List[Dict[str, Any]] = []
    for rec in records:
        out.extend(mon.observe(rec))
    return out


__all__ = ["HealthMonitor", "scan", "summarize", "RULES"]
