"""Step-metrics journal: per-step JSON-lines records for any training loop.

Generalizes bench.py's measurement discipline (its module docstring and
``_timed_windows``) into a reusable sink: every record carries wall time,
throughput, loss, loss-scale state, grad norm, rank info, and (optionally)
an HBM occupancy sample, one JSON object per line so any round's journal is
greppable and machine-joinable with the BENCH record.

Timing convention (CLAUDE.md tunnel discipline): the clock must stop on a
device→host fetch of a value whose dependency chain covers the step — never
on a bare ``block_until_ready`` (remote tunnels can ack dispatch rather than
execution). :meth:`MetricsJournal.step_end` therefore takes the step's loss
*array* and performs the ``float()`` fetch itself, so the recorded wall time
includes device execution by construction.

Zero hot-path syncs: the journal only touches device values after that loss
fetch, when the device is already drained; everything else (file write, HBM
sample via ``jax.live_arrays()``, rank lookup) is host-side.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, IO, Optional, Union


def _to_host(v):
    """Best-effort scalar conversion for record values; non-scalars pass
    through repr-able as-is (json.dumps(default=str) catches the rest)."""
    try:
        import numpy as np

        if hasattr(v, "dtype") or isinstance(v, (np.generic,)):
            arr = np.asarray(v)
            if arr.size == 1:
                x = arr.reshape(()).item()
                return bool(x) if arr.dtype == bool else x
            return arr.tolist()
    except Exception:  # noqa: BLE001 - a journal write must never raise
        pass
    return v


def scaler_state(scaler) -> Dict[str, Any]:
    """Loss-scale state snapshot from an ``amp.scaler.LossScaler`` (the
    same pytree the legacy ``fp16_utils.loss_scaler`` wrappers return):
    scale value + clean-step counter. Host fetch of two scalars — call
    after the step's loss fetch, not inside the timed region."""
    return {
        "loss_scale": _to_host(scaler.loss_scale),
        "unskipped": _to_host(scaler.unskipped),
    }


class MetricsJournal:
    """Append-only JSON-lines step journal.

    >>> journal = MetricsJournal("out/train.jsonl", sample_hbm_every=10)
    >>> for step in range(steps):
    ...     journal.step_start()
    ...     params, opt_state, loss, metrics = train_step(...)
    ...     journal.step_end(step=step, loss=loss, tokens=batch * seq,
    ...                      metrics=metrics, scaler=opt_state.scaler)
    >>> journal.close()

    ``metrics`` is the dict ``amp.MixedPrecisionOptimizer.apply_gradients``
    returns (``found_inf``, ``loss_scale``, and ``grad_norm`` when built
    with ``log_grad_norm=True``) or ``fp16_utils.FP16_Optimizer.step``'s
    ``info``; its scalars are fetched post-barrier and flattened into the
    record. Overflow/skip counts accumulate host-side from ``found_inf``.

    Lines are written with ``O_APPEND`` semantics, so concurrent processes
    (bench.py's fresh-subprocess phases) can share one journal file.
    """

    SCHEMA_VERSION = 1

    #: field names every ``step`` record carries (tests assert round-trip)
    STEP_FIELDS = ("v", "kind", "ts", "step", "wall_s", "rank", "rank_info")

    def __init__(
        self,
        path_or_file: Union[str, IO[str]],
        *,
        meta: Optional[Dict[str, Any]] = None,
        sample_hbm_every: int = 0,
        flush_every: int = 1,
    ):
        if hasattr(path_or_file, "write"):
            self._f, self._own = path_or_file, False
            self.path = getattr(path_or_file, "name", None)
        else:
            d = os.path.dirname(os.path.abspath(path_or_file))
            os.makedirs(d, exist_ok=True)
            self._f = open(path_or_file, "a")
            self._own = True
            self.path = path_or_file
        self.sample_hbm_every = int(sample_hbm_every)
        self.flush_every = max(int(flush_every), 1)
        self._since_flush = 0
        self._t0: Optional[float] = None
        self._n = 0
        self.overflows = 0  # cumulative found_inf count (skip counter)
        if meta:
            self.log(dict(meta, kind="meta"))

    # -- rank info (utils/log_util.py's RankInfoFilter, journal-side) -------
    @staticmethod
    def _rank_fields() -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        try:
            import jax

            out["rank"] = jax.process_index()
        except Exception:  # noqa: BLE001
            out["rank"] = 0
        try:
            from apex_tpu.transformer import parallel_state

            out["rank_info"] = parallel_state.get_rank_info_str()
        except Exception:  # noqa: BLE001
            out["rank_info"] = ""
        return out

    # -- core sink ----------------------------------------------------------
    def log(self, record: Dict[str, Any]) -> Dict[str, Any]:
        """Write one record (any dict); fills ``v``/``kind``/``ts``/rank
        fields, converts device scalars, never raises."""
        rec = {"v": self.SCHEMA_VERSION, "kind": record.get("kind", "step"),
               "ts": round(time.time(), 3)}
        rec.update(self._rank_fields())
        for k, v in record.items():
            rec[k] = _to_host(v)
        try:
            self._f.write(json.dumps(rec, default=str) + "\n")
            self._since_flush += 1
            if self._since_flush >= self.flush_every:
                self._f.flush()
                self._since_flush = 0
        except Exception:  # noqa: BLE001 - telemetry must not kill training
            pass
        return rec

    # -- the step protocol --------------------------------------------------
    def step_start(self) -> float:
        self._t0 = time.perf_counter()
        return self._t0

    def step_end(
        self,
        *,
        loss=None,
        tokens: Optional[int] = None,
        step: Optional[int] = None,
        metrics: Optional[Dict[str, Any]] = None,
        scaler=None,
        wall_s: Optional[float] = None,
        **extra,
    ) -> Dict[str, Any]:
        """Close the step opened by :meth:`step_start` and write its record.

        The ``float(loss)`` here IS the execution barrier (tunnel
        discipline): it stops the clock, so do not fetch the loss yourself
        first. ``wall_s`` overrides the internal clock for callers (like
        bench windows) that timed a multi-step region themselves.
        """
        loss_val = None
        if loss is not None:
            loss_val = float(loss)  # device→host fetch stops the clock
        if wall_s is None:
            wall_s = (time.perf_counter() - self._t0
                      if self._t0 is not None else None)
        self._t0 = None
        rec: Dict[str, Any] = {"kind": "step", "wall_s": wall_s}
        if step is not None:
            rec["step"] = step
        if loss_val is not None:
            rec["loss"] = loss_val
        if tokens is not None and wall_s:
            rec["tokens"] = int(tokens)
            rec["tokens_per_sec"] = round(tokens / wall_s, 1)
        if metrics:
            for k, v in metrics.items():
                rec[k] = _to_host(v)
            if rec.get("found_inf"):
                self.overflows += 1
        if scaler is not None:
            rec.update(scaler_state(scaler))
        rec["overflows"] = self.overflows
        rec.update(extra)
        self._n += 1
        if self.sample_hbm_every and self._n % self.sample_hbm_every == 0:
            try:
                from apex_tpu.monitor.hbm import live_array_stats

                rec["hbm"] = live_array_stats()
            except Exception:  # noqa: BLE001
                pass
        return self.log(rec)

    # -- lifecycle ----------------------------------------------------------
    def close(self):
        try:
            self._f.flush()
            if self._own:
                self._f.close()
        except Exception:  # noqa: BLE001
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    @staticmethod
    def read(path: str):
        """Parse a journal back into a list of dicts (schema round-trip)."""
        out = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out
