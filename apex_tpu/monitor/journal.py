"""Step-metrics journal: per-step JSON-lines records for any training loop.

Generalizes bench.py's measurement discipline (its module docstring and
``_timed_windows``) into a reusable sink: every record carries wall time,
throughput, loss, loss-scale state, grad norm, rank info, and (optionally)
an HBM occupancy sample, one JSON object per line so any round's journal is
greppable and machine-joinable with the BENCH record.

Timing convention (CLAUDE.md tunnel discipline): the clock must stop on a
device→host fetch of a value whose dependency chain covers the step — never
on a bare ``block_until_ready`` (remote tunnels can ack dispatch rather than
execution). :meth:`MetricsJournal.step_end` therefore takes the step's loss
*array* and performs the ``float()`` fetch itself, so the recorded wall time
includes device execution by construction.

Zero hot-path syncs: the journal only touches device values after that loss
fetch, when the device is already drained; everything else (file write, HBM
sample via ``jax.live_arrays()``, rank lookup) is host-side.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Dict, IO, List, Optional, Union


def _to_host(v):
    """Best-effort scalar conversion for record values (recursing into
    dict/list containers — e.g. ``grad_norm_by_group``); non-scalars pass
    through repr-able as-is (json.dumps(default=str) catches the rest)."""
    if isinstance(v, dict):
        return {k: _to_host(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_to_host(x) for x in v]
    try:
        import numpy as np

        if hasattr(v, "dtype") or isinstance(v, (np.generic,)):
            arr = np.asarray(v)
            if arr.size == 1:
                x = arr.reshape(()).item()
                return bool(x) if arr.dtype == bool else x
            return arr.tolist()
    except Exception:  # noqa: BLE001 - a journal write must never raise
        pass
    return v


def _sanitize_nonfinite(v, path: str, bad: List[str]):
    """Replace non-finite floats with None, recording their dotted key
    paths — every journal line must be STRICT JSON (``json.dumps``'s
    default ``allow_nan=True`` would emit bare ``NaN``/``Infinity``
    tokens a strict parser rejects), and the ``nonfinite_keys`` field is
    what the overflow forensics (monitor/diagnose.py) keys off."""
    if isinstance(v, float) and not math.isfinite(v):
        bad.append(path)
        return None
    if isinstance(v, dict):
        return {k: _sanitize_nonfinite(x, f"{path}.{k}" if path else str(k), bad)
                for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_sanitize_nonfinite(x, f"{path}[{i}]", bad)
                for i, x in enumerate(v)]
    return v


class JournalRecords(list):
    """``MetricsJournal.read``'s result: a plain list of record dicts
    plus parse metadata — ``truncated`` (the final non-empty line failed
    to parse: crash-/kill-time journals) and ``bad_lines`` (total
    unparseable lines, e.g. a torn write mid-file)."""

    truncated: bool = False
    bad_lines: int = 0


def scaler_state(scaler) -> Dict[str, Any]:
    """Loss-scale state snapshot from an ``amp.scaler.LossScaler`` (the
    same pytree the legacy ``fp16_utils.loss_scaler`` wrappers return):
    scale value + clean-step counter. Host fetch of two scalars — call
    after the step's loss fetch, not inside the timed region."""
    return {
        "loss_scale": _to_host(scaler.loss_scale),
        "unskipped": _to_host(scaler.unskipped),
    }


class MetricsJournal:
    """Append-only JSON-lines step journal.

    >>> journal = MetricsJournal("out/train.jsonl", sample_hbm_every=10)
    >>> for step in range(steps):
    ...     journal.step_start()
    ...     params, opt_state, loss, metrics = train_step(...)
    ...     journal.step_end(step=step, loss=loss, tokens=batch * seq,
    ...                      metrics=metrics, scaler=opt_state.scaler)
    >>> journal.close()

    ``metrics`` is the dict ``amp.MixedPrecisionOptimizer.apply_gradients``
    returns (``found_inf``, ``loss_scale``, and ``grad_norm`` when built
    with ``log_grad_norm=True``) or ``fp16_utils.FP16_Optimizer.step``'s
    ``info``; its scalars are fetched post-barrier and flattened into the
    record. Overflow/skip counts accumulate host-side from ``found_inf``.

    Lines are written with ``O_APPEND`` semantics, so concurrent processes
    (bench.py's fresh-subprocess phases) can share one journal file.
    """

    SCHEMA_VERSION = 1

    #: field names every ``step`` record carries (tests assert round-trip)
    STEP_FIELDS = ("v", "kind", "ts", "step", "wall_s", "rank", "rank_info")

    def __init__(
        self,
        path_or_file: Union[str, IO[str]],
        *,
        meta: Optional[Dict[str, Any]] = None,
        sample_hbm_every: int = 0,
        flush_every: int = 1,
        health=None,
    ):
        # online health rules (monitor/health.py): every record written
        # streams through the monitor's detectors and the resulting
        # kind="alert" rows append to this same journal (log() below) —
        # the "evaluated as records are written" wiring; None costs one
        # attribute check per log
        self.health = health
        if hasattr(path_or_file, "write"):
            self._f, self._own = path_or_file, False
            self.path = getattr(path_or_file, "name", None)
        else:
            d = os.path.dirname(os.path.abspath(path_or_file))
            os.makedirs(d, exist_ok=True)
            self._f = open(path_or_file, "a")
            self._own = True
            self.path = path_or_file
        self.sample_hbm_every = int(sample_hbm_every)
        self.flush_every = max(int(flush_every), 1)
        self._since_flush = 0
        self._t0: Optional[float] = None
        self._n = 0
        self.overflows = 0  # cumulative found_inf count (skip counter)
        self._step_costs: Optional[Dict[str, Any]] = None
        self._opt_state_bytes: Optional[int] = None
        self._param_bytes: Optional[int] = None
        self._step_comm: Optional[Dict[str, Any]] = None
        self._bubble: Optional[Dict[str, Any]] = None
        if meta:
            # provenance header (ISSUE 16): config fingerprint + the
            # environment stamp (git rev, jax/platform versions, peak
            # overrides) so ledger/report joins read provenance from the
            # journal instead of re-deriving it per harness. Bare
            # journals (meta omitted) stay record-for-record unchanged.
            header = dict(meta)
            try:
                from apex_tpu.monitor import ledger as _ledger

                header.setdefault(
                    "fingerprint", _ledger.config_fingerprint(meta))
                header.setdefault("env", _ledger.environment_stamp())
            except Exception:  # noqa: BLE001 - provenance is best-effort
                pass
            self.log(dict(header, kind="meta"))

    # -- MFU arming (monitor/mfu.py) ----------------------------------------
    def set_step_costs(
        self,
        *,
        flops_per_token: float,
        bytes_per_token: float = 0.0,
        platform: Optional[str] = None,
        method: str = "",
    ) -> None:
        """Arm per-record MFU/roofline fields: once set, every
        :meth:`step_end` record that carries ``tokens`` and a wall time
        also carries ``mfu``, ``hbm_bw_util``, ``bound``, ... joined
        from these per-token cost totals and the platform peak spec
        (``monitor.mfu.peak_spec`` — env-overridable through the
        tunnel). Host-side only; the compiled step is untouched."""
        from apex_tpu.monitor import mfu as _mfu  # lazy: journal stays light

        self._step_costs = {
            "flops_per_token": float(flops_per_token),
            "bytes_per_token": float(bytes_per_token),
            "spec": _mfu.peak_spec(platform),
        }
        if method:
            self._step_costs["method"] = method

    # -- step-anatomy arming (monitor/tracing.py) ---------------------------
    def set_step_comm(self, comm_bytes_per_step: float,
                      *, dcn_bytes_per_step: float = 0.0,
                      platform: Optional[str] = None) -> None:
        """Arm per-record step-anatomy fields: once set, every
        :meth:`step_end` record with a wall time also carries
        ``compute_frac``/``comm_frac``/``stall_frac`` (summing to 1.0)
        and ``overlap_fraction``, joined by ``monitor.tracing.
        step_anatomy`` from this per-step collective payload total
        (``monitor.comms`` accounting of the step trace), the armed
        step costs (:meth:`set_step_costs`) and the ICI bandwidth table
        (``APEX_TPU_PEAK_ICI_GBPS``-calibratable). Host-side only.

        On a two-tier pod mesh pass the slow-tier payload separately as
        ``dcn_bytes_per_step`` (``CommAccount.by_tier()['dcn']``): step
        records then also carry the per-link-class split ``ici_s`` /
        ``dcn_s`` (priced via ``tracing.dcn_spec`` —
        ``APEX_TPU_PEAK_DCN_GBPS``-calibratable) that ``report``'s tiers
        section and ``report compare --dcn-threshold`` consume."""
        from apex_tpu.monitor import tracing as _tracing  # lazy: stay light

        self._step_comm = {"bytes": float(comm_bytes_per_step),
                           "ici": _tracing.ici_spec(platform)}
        if dcn_bytes_per_step:
            self._step_comm["dcn_bytes"] = float(dcn_bytes_per_step)
            self._step_comm["dcn"] = _tracing.dcn_spec(platform)

    def set_bubble_fraction(self, measured: float,
                            expected: Optional[float] = None) -> None:
        """Arm a per-record ``bubble_fraction`` stamp: the measured
        per-rank pipeline bubble fraction (``schedules.
        traced_pipeline_timeline``'s anatomy) plus the analytic
        ``bubble_fraction_expected`` floor (``monitor.tracing.
        expected_bubble_fraction``), so journals from pipelined runs
        carry the schedule-quality claim ``report compare
        --bubble-threshold`` gates on."""
        self._bubble = {"bubble_fraction": round(float(measured), 4)}
        if expected is not None:
            self._bubble["bubble_fraction_expected"] = round(
                float(expected), 4)

    # -- optimizer-state arming (monitor/hbm.py) ----------------------------
    def set_opt_state_bytes(self, nbytes: int) -> None:
        """Arm a per-record ``opt_state_bytes`` field: the per-rank
        optimizer-state footprint (``monitor.hbm.opt_state_bytes`` of the
        live state — 1/dp of the replicated number under
        ``MixedPrecisionOptimizer(zero_axis=...)``). A static host-side
        value stamped into every subsequent step record so journals from
        replicated and ZeRO runs compare on the claim directly."""
        self._opt_state_bytes = int(nbytes)

    def set_param_bytes(self, nbytes: int) -> None:
        """Arm a per-record ``param_bytes`` field: the per-rank WORKING
        param footprint (``monitor.hbm.param_bytes`` of the live tree —
        1/dp of the replicated number under ``zero_level=3``, where the
        bf16 params persist as chunk trees). The companion of
        :meth:`set_opt_state_bytes`, so replicated/ZeRO-1/2/ZeRO-3
        journals compare on the full residency claim directly."""
        self._param_bytes = int(nbytes)

    # -- rank info (utils/log_util.py's RankInfoFilter, journal-side) -------
    @staticmethod
    def _rank_fields() -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        try:
            import jax

            out["rank"] = jax.process_index()
        except Exception:  # noqa: BLE001
            out["rank"] = 0
        try:
            from apex_tpu.transformer import parallel_state

            out["rank_info"] = parallel_state.get_rank_info_str()
        except Exception:  # noqa: BLE001
            out["rank_info"] = ""
        return out

    # -- core sink ----------------------------------------------------------
    def log(self, record: Dict[str, Any]) -> Dict[str, Any]:
        """Write one record (any dict); fills ``v``/``kind``/``ts``/rank
        fields, converts device scalars, never raises. Non-finite floats
        are written as ``null`` with their paths in ``nonfinite_keys``,
        so every line is STRICT JSON even when the loss goes NaN."""
        rec = {"v": self.SCHEMA_VERSION, "kind": record.get("kind", "step"),
               "ts": round(time.time(), 3)}
        rec.update(self._rank_fields())
        for k, v in record.items():
            rec[k] = _to_host(v)
        bad: List[str] = []
        rec = _sanitize_nonfinite(rec, "", bad)
        if bad:
            rec["nonfinite_keys"] = bad
        try:
            self._f.write(json.dumps(rec, default=str, allow_nan=False) + "\n")
            self._since_flush += 1
            if self._since_flush >= self.flush_every:
                self._f.flush()
                self._since_flush = 0
        except Exception:  # noqa: BLE001 - telemetry must not kill training
            pass
        try:
            # black-box feed (monitor/flight.py): an armed flight
            # recorder keeps the last records for the crash dump; a
            # single module-global check when disarmed
            from apex_tpu.monitor import flight as _flight

            _flight.observe_record(rec)
        except Exception:  # noqa: BLE001 - telemetry must not kill training
            pass
        if self.health is not None and rec.get("kind") != "alert":
            try:
                for alert in self.health.observe(rec):
                    self.log(alert)  # one level deep: alerts skip observe
            except Exception:  # noqa: BLE001 - telemetry must not kill work
                pass
        return rec

    def set_health(self, monitor) -> None:
        """Attach (or replace) the online health monitor after
        construction — harness paths that build the journal first."""
        self.health = monitor

    # -- the step protocol --------------------------------------------------
    def step_start(self) -> float:
        self._t0 = time.perf_counter()
        return self._t0

    def step_end(
        self,
        *,
        loss=None,
        tokens: Optional[int] = None,
        step: Optional[int] = None,
        metrics: Optional[Dict[str, Any]] = None,
        scaler=None,
        wall_s: Optional[float] = None,
        **extra,
    ) -> Dict[str, Any]:
        """Close the step opened by :meth:`step_start` and write its record.

        The ``float(loss)`` here IS the execution barrier (tunnel
        discipline): it stops the clock, so do not fetch the loss yourself
        first. ``wall_s`` overrides the internal clock for callers (like
        bench windows) that timed a multi-step region themselves.
        """
        loss_val = None
        if loss is not None:
            try:
                # hang-attribution breadcrumb (monitor/flight.py): this
                # fetch is where a wedged tunnel actually hangs — stamp
                # it BEFORE blocking so the watchdog kill report names it
                from apex_tpu.monitor import flight as _flight

                _flight.breadcrumb(f"fetch:loss[step={step}]")
            except Exception:  # noqa: BLE001 - telemetry must not raise
                pass
            loss_val = float(loss)  # device→host fetch stops the clock
        if wall_s is None:
            wall_s = (time.perf_counter() - self._t0
                      if self._t0 is not None else None)
        self._t0 = None
        rec: Dict[str, Any] = {"kind": "step", "wall_s": wall_s}
        if step is not None:
            rec["step"] = step
        if loss_val is not None:
            rec["loss"] = loss_val
        if tokens is not None and wall_s:
            rec["tokens"] = int(tokens)
            rec["tokens_per_sec"] = round(tokens / wall_s, 1)
            if self._step_costs is not None:
                try:
                    from apex_tpu.monitor import mfu as _mfu

                    rec.update(_mfu.mfu_metrics(
                        flops=self._step_costs["flops_per_token"] * tokens,
                        bytes_accessed=(self._step_costs["bytes_per_token"]
                                        * tokens),
                        wall_s=wall_s,
                        spec=self._step_costs["spec"]))
                    if self._step_costs.get("method"):
                        # jaxpr-armed bytes are a pre-fusion upper bound
                        # (mfu.traced_step_costs); readers need to know
                        rec["mfu_method"] = self._step_costs["method"]
                except Exception:  # noqa: BLE001 - telemetry must not raise
                    pass
        if metrics:
            for k, v in metrics.items():
                rec[k] = _to_host(v)
            if rec.get("found_inf"):
                self.overflows += 1
        if scaler is not None:
            rec.update(scaler_state(scaler))
        if self._step_comm is not None and wall_s:
            try:
                from apex_tpu.monitor import tracing as _tracing

                flops = None
                spec = None
                if self._step_costs is not None and tokens:
                    flops = self._step_costs["flops_per_token"] * tokens
                    spec = self._step_costs["spec"]
                an = _tracing.step_anatomy(
                    wall_s=wall_s, flops=flops, spec=spec,
                    comm_bytes=self._step_comm["bytes"],
                    ici=self._step_comm["ici"],
                    dcn_bytes=self._step_comm.get("dcn_bytes"),
                    dcn=self._step_comm.get("dcn"))
                for k in ("compute_s", "comm_s", "host_stall_s",
                          "compute_frac", "comm_frac", "stall_frac",
                          "overlap_fraction", "ici_s", "dcn_s"):
                    if k in an:
                        rec[k] = an[k]
            except Exception:  # noqa: BLE001 - telemetry must not raise
                pass
        if self._bubble is not None:
            rec.update(self._bubble)
        if self._opt_state_bytes is not None:
            rec["opt_state_bytes"] = self._opt_state_bytes
        if self._param_bytes is not None:
            rec["param_bytes"] = self._param_bytes
        rec["overflows"] = self.overflows
        rec.update(extra)
        self._n += 1
        if self.sample_hbm_every and self._n % self.sample_hbm_every == 0:
            try:
                from apex_tpu.monitor.hbm import live_array_stats

                rec["hbm"] = live_array_stats()
            except Exception:  # noqa: BLE001
                pass
        return self.log(rec)

    # -- lifecycle ----------------------------------------------------------
    def close(self):
        try:
            self._f.flush()
            if self._own:
                self._f.close()
        except Exception:  # noqa: BLE001
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    @staticmethod
    def read(path: str) -> JournalRecords:
        """Parse a journal back into a list of dicts (schema round-trip).

        Tolerates a truncated/corrupt final line — a journal cut mid-write
        by a crash or a watchdog kill must still parse (the whole point of
        a crash-time journal). Good records come back as a
        :class:`JournalRecords` list whose ``truncated`` flag marks a
        broken final line and ``bad_lines`` counts every unparseable one.
        """
        out = JournalRecords()
        last_bad = False  # streaming: never hold the raw file in memory
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    obj = None
                if not isinstance(obj, dict):
                    # unparseable OR a torn fragment that happens to be
                    # valid scalar JSON ("42") — either way not a record
                    out.bad_lines += 1
                    last_bad = True
                    continue
                out.append(obj)
                last_bad = False
        out.truncated = last_bad
        return out
