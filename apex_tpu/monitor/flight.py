"""Flight recorder: bounded black-box ring + crash dump + breadcrumbs.

Every observability layer before this one (journal → report → tracing →
IR audit) is post-hoc: it explains a run after it ends. The co-tenant
chip's failure regimes (PERF_NOTES r5: OOM, steady occupation, WEDGED
tunnel) kill the process mid-step, leaving stderr and — at best — a
torn journal tail. This module is the in-process black box:

- **Ring**: a bounded in-memory deque of the most recent journal
  records, span events, and breadcrumbs (``MetricsJournal.log`` and
  ``tracing.Tracer.log`` feed it automatically when armed — zero wiring
  in harness loops, zero cost disarmed).
- **Breadcrumbs**: :func:`breadcrumb` stamps the "operation being
  entered" — wired at the device→host fetch points
  (``tracing.fetch_barrier``, the journal's loss fetch: where a wedged
  tunnel hangs a COMPILED step at runtime) and at the ``comm:``
  collective scopes (``monitor/comms.py``: trace-time + the eager
  per-tick drives, attributing compile-/trace-time hangs). The latest
  breadcrumb also rides the structured heartbeat
  (``monitor/watchdog.py``), so a watchdog kill report names the last
  operation the child entered before wedging.
- **Dump**: on unhandled exception (``sys.excepthook`` chain), fatal
  signal (SIGTERM handler), or explicit :func:`dump`, the ring lands as
  ONE strict-JSON crash file — default ``<journal>.flight.json`` — with
  an HBM/live-array snapshot, the last loss-scale state seen in the
  ring, and the last breadcrumb. Written atomically (temp + rename,
  ``utils/io.py``) so a crash mid-dump never publishes a torn artifact;
  :func:`load` degrades to None on a corrupt file instead of raising.

Armed via :func:`arm` (harness ``--flight``), ``APEX_TPU_FLIGHT=<path>``
(lazy, like ``APEX_TPU_TRACE``), or ``BENCH_FLIGHT`` in bench.py.
Disarmed, compiled step/serve programs are byte-identical (breadcrumbs
and ring feeds are host-side and short-circuit on a module global;
tier-1 pins the discipline, same as ``--trace``).

No reference-file citation: like the rest of apex_tpu.monitor, NVIDIA
Apex has no telemetry layer; the black-box framing follows veScale's
production-debuggability thesis (PAPERS.md).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time
import traceback as _traceback
from collections import deque
from typing import Any, Dict, Optional

from apex_tpu.monitor.journal import _sanitize_nonfinite, _to_host
from apex_tpu.utils.io import atomic_write_json

ENV_FLIGHT = "APEX_TPU_FLIGHT"

#: ring capacity default — enough for ~100 steps of journal + span +
#: breadcrumb traffic without holding a long run's history
DEFAULT_CAPACITY = 512

_GLOBAL: Optional["FlightRecorder"] = None
_ENV_CHECKED = False

#: the latest operation entered (host-side): {"op", "ts"} — always
#: tracked (a plain dict assignment, effectively free) so the structured
#: heartbeat can name it even when no recorder is armed
_LAST_OP: Optional[Dict[str, Any]] = None

#: the last watchdog stage beaten (watchdog.Heartbeat.beat records it
#: here so breadcrumb-driven heartbeat refreshes preserve the stage)
_LAST_STAGE: str = ""

# cached child-side heartbeat writer: None = unchecked, False = no env
_HB: Any = None

#: zero-arg callable returning the in-flight request table (serve/
#: engine.py registers its own around run()) — the crash dump names the
#: REQUESTS a wedged serve was sitting on, not just the op
_INFLIGHT_PROVIDER: Any = None


def last_op() -> Optional[Dict[str, Any]]:
    """The most recent breadcrumb (``{"op", "ts"}``), or None."""
    return _LAST_OP


def set_stage(stage: str) -> None:
    """Record the current watchdog stage (``Heartbeat.beat`` calls this)
    so breadcrumb heartbeat refreshes carry it forward."""
    global _LAST_STAGE
    _LAST_STAGE = str(stage)


def _heartbeat():
    """Child-side heartbeat writer from the watchdog env, cached."""
    global _HB
    if _HB is None:
        try:
            from apex_tpu.monitor.watchdog import Heartbeat

            _HB = Heartbeat.from_env() or False
        except Exception:  # noqa: BLE001 - telemetry must not kill work
            _HB = False
    return _HB or None


def reset_heartbeat_cache() -> None:
    """Re-read the heartbeat env on next breadcrumb (tests, subprocess
    re-exec paths that mutate ``APEX_TPU_HEARTBEAT_PATH``)."""
    global _HB
    _HB = None


def set_inflight_provider(fn) -> None:
    """Register (or clear, with None) the zero-arg callable whose return
    value lands in crash dumps as ``inflight_requests`` — the serving
    engine's in-flight request table (ISSUE 17). Host-side only; the
    provider is called guarded at dump time, never during serving."""
    global _INFLIGHT_PROVIDER
    _INFLIGHT_PROVIDER = fn


def breadcrumb(op: str, **attrs) -> None:
    """Stamp "about to enter ``op``" — the hang-attribution primitive.

    Called at the ``comm:`` scope entries and device→host fetch points.
    Three effects, each skipped when its consumer is absent: update the
    module-level last-op (always; one dict assignment), append a
    breadcrumb record to the armed ring, and refresh the structured
    heartbeat file so a watchdog kill report names this operation.
    Never raises.
    """
    global _LAST_OP
    rec = {"op": str(op), "ts": round(time.time(), 6)}
    if attrs:
        rec.update(attrs)
    _LAST_OP = rec
    fr = get_recorder()  # lazy APEX_TPU_FLIGHT arming rides the lookup
    if fr is not None:
        fr.note(dict(rec, kind="breadcrumb"))
    hb = _heartbeat()
    if hb is not None:
        try:
            hb.beat(_LAST_STAGE)
        except Exception:  # noqa: BLE001 - see docstring
            pass


def observe_record(rec: Dict[str, Any]) -> None:
    """Feed one already-sanitized journal/span record into the armed
    ring (``MetricsJournal.log`` / ``Tracer.log`` call this). A single
    global check when disarmed (after the one-time env probe); never
    raises."""
    fr = get_recorder()  # lazy APEX_TPU_FLIGHT arming rides the lookup
    if fr is not None:
        fr.note(rec)


class FlightRecorder:
    """The black box: bounded ring + crash-file dump.

    >>> fr = flight.arm("out/train.jsonl.flight.json",
    ...                 meta={"run": "pretrain_gpt"})
    >>> ...train (journal/tracer records + breadcrumbs feed the ring)...
    >>> fr.dump("explicit")     # or let the excepthook/SIGTERM hook fire

    ``dump`` is idempotent per reason-free crash path (the first crash
    wins; an explicit dump can always be re-taken).
    """

    def __init__(self, path: str, *, capacity: int = DEFAULT_CAPACITY,
                 meta: Optional[Dict[str, Any]] = None):
        self.path = path
        self.meta = dict(meta or {})
        self.ring: deque = deque(maxlen=max(int(capacity), 16))
        self.dumped: Optional[str] = None  # reason of the first dump

    def note(self, record: Dict[str, Any]) -> None:
        try:
            self.ring.append(record)
        except Exception:  # noqa: BLE001 - telemetry must not kill work
            pass

    # -- the crash artifact -------------------------------------------------
    def snapshot(self, reason: str, exc=None) -> Dict[str, Any]:
        """Assemble the dump payload (host-side; HBM sampling guarded —
        a wedged backend must not wedge the dump too)."""
        payload: Dict[str, Any] = {
            "v": 1, "kind": "flight", "reason": str(reason),
            "ts": round(time.time(), 3), "pid": os.getpid(),
            "meta": self.meta, "last_op": _LAST_OP, "stage": _LAST_STAGE,
        }
        if exc is not None:
            payload["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc)[:500],
                "traceback": "".join(_traceback.format_exception(
                    type(exc), exc, exc.__traceback__))[-4000:],
            }
        # loss-scale state: the newest ring record carrying a scale
        for rec in reversed(self.ring):
            if isinstance(rec, dict) and "loss_scale" in rec:
                payload["scaler"] = {
                    "loss_scale": rec.get("loss_scale"),
                    "unskipped": rec.get("unskipped"),
                    "step": rec.get("step"),
                }
                break
        try:
            from apex_tpu.monitor.hbm import live_array_stats

            payload["hbm"] = live_array_stats()
        except Exception:  # noqa: BLE001 - no backend / wedged backend
            payload["hbm"] = None
        if _INFLIGHT_PROVIDER is not None:
            try:
                payload["inflight_requests"] = _INFLIGHT_PROVIDER()
            except Exception:  # noqa: BLE001 - a bad provider must not
                payload["inflight_requests"] = None  # spoil the dump
        payload["ring"] = [_to_host(r) for r in self.ring]
        bad: list = []
        payload = _sanitize_nonfinite(payload, "", bad)
        if bad:
            payload["nonfinite_keys"] = bad
        return payload

    def dump(self, reason: str = "explicit", exc=None) -> Optional[str]:
        """Write the crash file (strict JSON, atomic). Returns the path,
        or None when the write failed — a dump must never raise into the
        crashing frame above it."""
        try:
            atomic_write_json(self.path, self.snapshot(reason, exc),
                              indent=1)
            self.dumped = reason
            return self.path
        except Exception:  # noqa: BLE001 - see docstring
            return None


# ---------------------------------------------------------------------------
# global arming + crash hooks
# ---------------------------------------------------------------------------

_PREV_EXCEPTHOOK = None
_PREV_SIGTERM = None


def _flight_excepthook(exc_type, exc, tb):
    fr = _GLOBAL
    if fr is not None and fr.dumped is None:
        e = exc if isinstance(exc, BaseException) else exc_type(exc)
        e.__traceback__ = tb
        fr.dump("unhandled_exception", e)
    hook = _PREV_EXCEPTHOOK or sys.__excepthook__
    hook(exc_type, exc, tb)


def _flight_sigterm(signum, frame):
    fr = _GLOBAL
    if fr is not None and fr.dumped is None:
        fr.dump(f"signal:{signum}")
    # restore + re-raise so the exit status stays a genuine signal death
    try:
        signal.signal(signum, _PREV_SIGTERM or signal.SIG_DFL)
        os.kill(os.getpid(), signum)
    except Exception:  # noqa: BLE001 - fall back to a plain exit
        sys.exit(128 + signum)


def arm(path: str, *, meta: Optional[Dict[str, Any]] = None,
        capacity: int = DEFAULT_CAPACITY,
        hooks: bool = True) -> FlightRecorder:
    """Install the process-global flight recorder (replacing any
    previous one). ``hooks=True`` chains ``sys.excepthook`` and a
    SIGTERM handler so crashes dump without harness wiring; pass False
    for in-process tests that manage dumps themselves."""
    global _GLOBAL, _ENV_CHECKED, _PREV_EXCEPTHOOK, _PREV_SIGTERM
    _GLOBAL = FlightRecorder(path, capacity=capacity, meta=meta)
    _ENV_CHECKED = True
    if hooks:
        if sys.excepthook is not _flight_excepthook:
            _PREV_EXCEPTHOOK = sys.excepthook
            sys.excepthook = _flight_excepthook
        try:
            prev = signal.getsignal(signal.SIGTERM)
            if prev is not _flight_sigterm:
                _PREV_SIGTERM = prev
                signal.signal(signal.SIGTERM, _flight_sigterm)
        except (ValueError, OSError):
            pass  # non-main thread / exotic platform: excepthook only
    return _GLOBAL


def disarm() -> None:
    """Remove the recorder, restore any chained hooks, and clear the
    breadcrumb state — a later arm in the same process must not
    attribute its crashes to an operation from a previous segment."""
    global _GLOBAL, _ENV_CHECKED, _PREV_EXCEPTHOOK, _PREV_SIGTERM
    global _LAST_OP, _LAST_STAGE, _INFLIGHT_PROVIDER
    _GLOBAL = None
    _ENV_CHECKED = True
    _LAST_OP = None
    _LAST_STAGE = ""
    _INFLIGHT_PROVIDER = None
    if sys.excepthook is _flight_excepthook:
        sys.excepthook = _PREV_EXCEPTHOOK or sys.__excepthook__
        _PREV_EXCEPTHOOK = None
    try:
        if signal.getsignal(signal.SIGTERM) is _flight_sigterm:
            signal.signal(signal.SIGTERM, _PREV_SIGTERM or signal.SIG_DFL)
            _PREV_SIGTERM = None
    except (ValueError, OSError):
        pass


def get_recorder() -> Optional[FlightRecorder]:
    """The armed recorder, or None. ``APEX_TPU_FLIGHT=<path>`` arms
    lazily on first lookup (the env opt-in, mirroring tracing)."""
    global _GLOBAL, _ENV_CHECKED
    if _GLOBAL is None and not _ENV_CHECKED:
        _ENV_CHECKED = True
        path = os.environ.get(ENV_FLIGHT)
        if path:
            try:
                arm(path)
            except Exception:  # noqa: BLE001 - telemetry must not kill a run
                _GLOBAL = None
    return _GLOBAL


def armed() -> bool:
    return get_recorder() is not None


def dump(reason: str = "explicit", exc=None) -> Optional[str]:
    """Dump the armed recorder's ring now (None when disarmed)."""
    fr = get_recorder()
    return fr.dump(reason, exc) if fr is not None else None


# ---------------------------------------------------------------------------
# tolerant load + parent-side kill dump
# ---------------------------------------------------------------------------


def load(path: str) -> Optional[Dict[str, Any]]:
    """Read a flight dump back; None on missing/corrupt/torn files
    (journal-style tolerance — a crash artifact consumer must never
    crash on the artifact)."""
    try:
        with open(path) as f:
            obj = json.load(f)
        return obj if isinstance(obj, dict) else None
    except (OSError, ValueError):
        return None


def write_kill_dump(path: str, *, reason: str, status: str,
                    heartbeat: Optional[Dict[str, Any]] = None,
                    checkpoint: Optional[Dict[str, Any]] = None,
                    newer_than: Optional[float] = None) -> bool:
    """Parent-side flight dump after a SIGKILL: the child's in-memory
    ring died with it, so the watchdog writes what survived — the
    structured heartbeat (stage + last breadcrumb) and the last durable
    checkpoint. Skipped when the child already dumped (its file wins) —
    but only if that dump is fresher than ``newer_than`` (the child's
    start time): a stale artifact from a PREVIOUS run must not suppress
    this kill's evidence. Returns True when a file was written."""
    if load(path) is not None:
        try:
            fresh = (newer_than is None
                     or os.path.getmtime(path) >= newer_than)
        except OSError:
            fresh = False
        if fresh:
            return False
    hb = heartbeat or {}
    payload = {
        "v": 1, "kind": "flight", "reason": str(reason),
        "status": str(status), "ts": round(time.time(), 3),
        "writer": "watchdog-parent", "pid": os.getpid(),
        "last_op": hb.get("last_op"), "stage": hb.get("stage"),
        "heartbeat": heartbeat, "checkpoint": checkpoint, "ring": [],
    }
    try:
        atomic_write_json(path, payload, indent=1)
        return True
    except Exception:  # noqa: BLE001 - a kill report must not kill the parent
        return False


__all__ = [
    "FlightRecorder", "arm", "disarm", "get_recorder", "armed", "dump",
    "breadcrumb", "observe_record", "last_op", "set_stage", "load",
    "write_kill_dump", "reset_heartbeat_cache", "set_inflight_provider",
    "ENV_FLIGHT", "DEFAULT_CAPACITY",
]
