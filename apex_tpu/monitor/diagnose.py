"""Overflow/NaN forensics + recompile tracking: attributable diagnoses.

PR 1 left the journal describing *that* a step overflowed (``found_inf``,
cumulative ``overflows``) or slowed down; this module answers *why* from
the journal alone:

- :class:`OverflowForensics` — an opt-in host-side hook over
  ``MixedPrecisionOptimizer``'s step metrics. On ``found_inf`` (or a
  non-finite / spiking loss) it dumps ONE forensic record: the
  per-parameter-group grad-norm breakdown (build the optimizer with
  ``log_group_norms=True``; a group whose norm is non-finite names the
  first non-finite layer), the recent loss-scale history, and the
  cumulative-overflow trajectory — the evidence discipline EQuARX
  (PAPERS.md, arxiv 2506.17615) applies to collective changes, applied
  to loss-scale events. Pure host code after the step's loss fetch:
  compiled programs are untouched.
- :class:`RecompileTracker` — wraps a jitted callable and counts jit
  cache misses and seconds spent in miss calls per argument-shape
  signature (the shape-churn detector: a training loop that recompiles
  every step because a batch dimension wobbles shows up as one
  signature per step in the journal instead of a mystery slowdown).

Both emit ``kind="forensics"`` / ``kind="recompile"`` journal rows that
``python -m apex_tpu.monitor.report`` rolls up.

No reference-file citation: NVIDIA Apex logs overflow skips to stdout
(apex/amp/handle.py's "Gradient overflow" print) and has no recompile
concept; both diagnoses here are TPU/XLA-native extensions.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional


def group_grad_norms(grads, psum_axis=None, extra_axes=None) -> Dict[str, Any]:
    """Per-parameter-group L2 norms of a grad pytree (traced-safe).

    Top-level dict keys are the groups (``wte``/``layers``/... for the
    GPT models); a non-dict tree reports one ``<params>`` row. The
    per-group reduction reuses ``tree_l2norm`` so the breakdown matches
    the global ``grad_norm`` metric's semantics exactly.

    ``psum_axis``: when every leaf is a 1/n shard of the true tensor (the
    ZeRO chunks of ``MixedPrecisionOptimizer(zero_axis=...)``), per-group
    squared partials are psum'd over that mesh axis before the sqrt, so
    the breakdown reports the same numbers as the replicated path.
    ``extra_axes`` (a pytree matching ``grads`` whose leaves are tuples
    of mesh-axis names) additionally psums each leaf over the axes its
    param is SHARDED over, so tp/pp-hybrid meshes also match.
    """
    from apex_tpu.ops.multi_tensor import tree_l2norm

    if psum_axis is None:
        def norm(tree, extras=None):
            return tree_l2norm(tree)
    else:
        import jax.numpy as jnp

        from apex_tpu.optimizers._common import sharded_tree_sumsq

        def norm(tree, extras=None):
            return jnp.sqrt(sharded_tree_sumsq(tree, psum_axis, extras))

    if isinstance(grads, dict) and grads:
        return {str(k): norm(v, None if extra_axes is None
                             else extra_axes[k])
                for k, v in grads.items()}
    return {"<params>": norm(grads, extra_axes)}


def _scalar(v) -> Optional[float]:
    try:
        return float(v)
    except Exception:  # noqa: BLE001 - absent/odd metric values
        return None


def _isfinite(x: Optional[float]) -> bool:
    return x is not None and x == x and abs(x) != float("inf")


def median(values) -> Optional[float]:
    """Plain median (None on empty) — shared by the forensics baseline
    and ``monitor.report``'s offline rollups."""
    s = sorted(values)
    if not s:
        return None
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def is_loss_spike(loss: float, baseline: Optional[float],
                  spike_factor: float) -> bool:
    """THE spike predicate — the one copy shared by the online
    :class:`OverflowForensics` trigger and ``report.analyze``'s offline
    spike list, so the two can never silently desynchronize."""
    return (baseline is not None
            and abs(loss) > spike_factor * max(abs(baseline), 1e-12))


class OverflowForensics:
    """Host-side overflow / loss-spike forensics over step metrics.

    >>> forensics = OverflowForensics(journal)
    >>> for step in range(steps):
    ...     params, opt_state, loss, metrics = train_step(...)
    ...     journal.step_end(step=step, loss=loss, metrics=metrics, ...)
    ...     forensics.observe(step=step, loss=loss, metrics=metrics)

    Call AFTER the journal's loss fetch (the device is drained; the
    extra scalar fetches here are free). ``observe`` returns the
    forensic record when this step triggered one, else None.
    """

    def __init__(
        self,
        journal=None,
        *,
        history: int = 64,
        spike_window: int = 16,
        spike_factor: float = 3.0,
    ):
        self.journal = journal
        self.spike_factor = float(spike_factor)
        #: (step, loss_scale) trail — the scale's recent trajectory
        self.scale_history: deque = deque(maxlen=int(history))
        #: recent FINITE, non-overflow losses — the spike baseline
        self._losses: deque = deque(maxlen=int(spike_window))
        #: steps that overflowed (cumulative trajectory)
        self.overflow_steps: List[Any] = []
        self.records: List[Dict[str, Any]] = []

    # -- trigger logic ------------------------------------------------------
    def _spike_baseline(self) -> Optional[float]:
        if len(self._losses) < 4:
            return None  # too little history to call anything a spike
        return median(self._losses)

    def observe(
        self,
        *,
        step=None,
        loss=None,
        metrics: Optional[Dict[str, Any]] = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> Optional[Dict[str, Any]]:
        """Feed one step's host-side evidence; emit a record on trigger."""
        metrics = metrics or {}
        loss_val = _scalar(loss)
        scale = _scalar(metrics.get("loss_scale"))
        found_inf = bool(_scalar(metrics.get("found_inf")) or 0.0)
        if scale is not None:
            self.scale_history.append((step, scale))

        trigger = None
        baseline = self._spike_baseline()
        if found_inf:
            trigger = "overflow"
        elif loss_val is not None and not _isfinite(loss_val):
            trigger = "nonfinite_loss"
        elif loss_val is not None and is_loss_spike(loss_val, baseline,
                                                    self.spike_factor):
            trigger = "loss_spike"

        if found_inf:
            self.overflow_steps.append(step)
        elif _isfinite(loss_val):
            self._losses.append(loss_val)

        if trigger is None:
            return None

        rec: Dict[str, Any] = {
            "kind": "forensics",
            "trigger": trigger,
            "step": step,
            "loss": loss_val,
            "loss_scale": scale,
            "spike_baseline": baseline,
            "overflows_total": len(self.overflow_steps),
            "overflow_steps": self.overflow_steps[-16:],
            "scale_history": [[s, v] for s, v in list(self.scale_history)[-16:]],
        }
        gn = _scalar(metrics.get("grad_norm"))
        if gn is not None:
            rec["grad_norm"] = gn
        by_group = metrics.get("grad_norm_by_group")
        if isinstance(by_group, dict):
            norms = {k: _scalar(v) for k, v in by_group.items()}
            rec["grad_norm_by_group"] = norms
            # the attribution ask: WHICH group went non-finite first
            rec["nonfinite_groups"] = sorted(
                k for k, v in norms.items() if not _isfinite(v))
        if extra:
            rec.update(extra)
        self.records.append(rec)
        if self.journal is not None:
            self.journal.log(dict(rec))
        return rec

    def summary(self) -> Dict[str, Any]:
        by_trigger: Dict[str, int] = {}
        for r in self.records:
            by_trigger[r["trigger"]] = by_trigger.get(r["trigger"], 0) + 1
        return {"records": len(self.records), "by_trigger": by_trigger,
                "overflow_steps": list(self.overflow_steps)}


# ---------------------------------------------------------------------------
# recompile tracking
# ---------------------------------------------------------------------------


def _jit_cache_size(fn) -> Optional[int]:
    """Best-effort jit cache size (None when the wrapped callable is not
    a jitted function or the private accessor moved)."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:  # noqa: BLE001
        return None


def _arg_signature(args, kwargs) -> str:
    """Stable shape/dtype signature of a call's arguments (the jit cache
    key's observable part: avals, not values)."""
    import jax

    parts = []
    for leaf in jax.tree.leaves((args, kwargs)):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None:
            parts.append(f"{dtype}{list(shape)}")
        else:
            parts.append(type(leaf).__name__)
    return ";".join(parts)


class RecompileTracker:
    """Count jit cache misses + seconds per function and arg signature.

    >>> tracker = RecompileTracker(journal)
    >>> train_step = tracker.wrap(jax.jit(step), name="train_step")
    >>> ... call train_step as usual ...
    >>> tracker.summary()
    {'train_step': {'calls': 12, 'compiles': 2, 'compile_s': 31.2,
                    'signatures': 2}}

    A miss is detected from the jit cache growing across the call (the
    authoritative signal); when the private cache probe is unavailable
    the first call per shape/dtype signature counts instead.
    ``compile_s`` is the wall time of miss calls — trace + compile +
    first execution, the operator-facing cost of shape churn. Each miss
    also lands a ``kind="recompile"`` journal row.
    """

    def __init__(self, journal=None):
        self.journal = journal
        self.stats: Dict[str, Dict[str, Any]] = {}

    def wrap(self, fn: Callable, name: Optional[str] = None) -> Callable:
        import functools

        label = name or getattr(fn, "__name__", None) or repr(fn)
        entry = self.stats.setdefault(
            label, {"calls": 0, "compiles": 0, "compile_s": 0.0,
                    "signatures": {}})

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            sig = _arg_signature(args, kwargs)
            before = _jit_cache_size(fn)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            dt = time.perf_counter() - t0
            after = _jit_cache_size(fn)
            if after is not None and before is not None:
                missed = after > before
            else:
                missed = sig not in entry["signatures"]
            entry["calls"] += 1
            sig_row = entry["signatures"].setdefault(
                sig, {"calls": 0, "compiles": 0, "compile_s": 0.0})
            sig_row["calls"] += 1
            if missed:
                entry["compiles"] += 1
                entry["compile_s"] += dt
                sig_row["compiles"] += 1
                sig_row["compile_s"] += dt
                if self.journal is not None:
                    self.journal.log({
                        "kind": "recompile", "fn": label,
                        "signature": sig[:200], "compile_s": round(dt, 4),
                        "compiles_total": entry["compiles"],
                        "cache_size": after,
                    })
            return out

        wrapped.tracker_stats = entry
        return wrapped

    def summary(self) -> Dict[str, Dict[str, Any]]:
        """Per-function rollup (signature count, not the full map)."""
        return {
            name: {"calls": e["calls"], "compiles": e["compiles"],
                   "compile_s": round(e["compile_s"], 4),
                   "signatures": len(e["signatures"])}
            for name, e in self.stats.items()
        }

    def shape_churn(self, threshold: int = 3) -> Dict[str, int]:
        """Functions compiled for more than ``threshold`` signatures —
        the classic unpadded-batch/varying-seq defect."""
        return {name: len(e["signatures"]) for name, e in self.stats.items()
                if len(e["signatures"]) > threshold}
