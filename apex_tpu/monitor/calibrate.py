"""Cost-model calibration: predicted-vs-measured joins + fitted peaks.

The repo predicts a run (static-hbm peak bytes, comm bytes per verb,
``tracing.expected_bubble_fraction`` floors, pyprof FLOPs → modeled step
seconds) and measures one (journal → ``report.analyze``); the run ledger
(``monitor/ledger.py``) persists both blocks per completed run. This
module closes the loop:

- :func:`join` — per-record error ratios (measured / predicted) for each
  model: ``hbm_ratio`` (measured peak live bytes over the static
  estimate), ``bubble_ratio`` (measured bubble fraction over the
  analytic floor), ``comm_ratio`` (booked collective bytes over the
  static census), ``wall_ratio`` (measured step seconds over the
  modeled compute+wire seconds).
- :func:`fit` — effective peak constants from many records: the peak
  FLOP/s and ICI GB/s that make the cost model's compute/comm seconds
  meet the measured walls — exactly the denominators
  ``mfu.peak_spec``/``tracing.ici_spec`` consume today via the
  ``APEX_TPU_PEAK_*`` env knobs, fitted instead of hand-set.
- :func:`save`/:func:`load`/:func:`active` — the calibration file.
  Arming is explicit: set ``APEX_TPU_CALIBRATION=<path>`` (or pass the
  file to a consumer) and ``peak_spec``/``ici_spec`` resolve their
  constants from it with ``source="calibrated"``. **When armed, the
  file takes precedence over the ``APEX_TPU_PEAK_*`` env overrides**
  (a fitted constant from real measurements outranks a hand-typed one);
  when the env var is unset nothing changes — disarmed programs and
  their journals stay byte-identical.

Pure host-side stdlib (+ ``utils/io`` for the atomic write): no jax
import, safe inside ``peak_spec`` on any platform.

No reference-file citation: NVIDIA Apex has no cost-model layer; this
is the calibration substrate ROADMAP items 2/3 (DCN tier model,
auto-parallelism planner) read from.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

ENV_CALIBRATION = "APEX_TPU_CALIBRATION"

SCHEMA_VERSION = 1

#: keys a calibration file may carry, all optional: peak FLOP/s, ICI
#: bytes/s, DCN bytes/s and HBM bytes/s denominators (absolute units,
#: not GB/s).
FITTED_KEYS = ("peak_flops", "peak_ici_bytes_per_sec",
               "peak_dcn_bytes_per_sec", "peak_hbm_bytes_per_sec")

# one-entry (path, mtime) cache: peak_spec may resolve once per journal
# record arming; re-stat instead of re-parse when the file is unchanged
_CACHE: Dict[str, Any] = {}


def _median(vals: List[float]) -> Optional[float]:
    s = sorted(v for v in vals if isinstance(v, (int, float)) and v > 0)
    if not s:
        return None
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


# ---------------------------------------------------------------------------
# the calibration file
# ---------------------------------------------------------------------------


def save(path: str, calibration: Dict[str, Any]) -> str:
    """Atomically write a calibration file (``utils/io`` discipline —
    a torn calibration would silently poison every later denominator)."""
    from apex_tpu.utils.io import atomic_write_json

    out = {"v": SCHEMA_VERSION}
    out.update(calibration)
    return atomic_write_json(path, out)


def load(path: str) -> Optional[Dict[str, Any]]:
    """Read a calibration file; None on a missing/corrupt/alien file
    (a consumer must degrade to its table row, never crash)."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except Exception:  # noqa: BLE001 - degrade to the table row
        return None
    if not isinstance(obj, dict):
        return None
    if not any(isinstance(obj.get(k), (int, float)) and obj[k] > 0
               for k in FITTED_KEYS):
        return None
    return obj


def active() -> Optional[Dict[str, Any]]:
    """The armed calibration: the ``APEX_TPU_CALIBRATION`` file when the
    env var is set and the file parses, else None. Cached by (path,
    mtime) so per-record consumers don't re-parse an unchanged file."""
    path = os.environ.get(ENV_CALIBRATION)
    if not path:
        return None
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        return None
    if _CACHE.get("path") == path and _CACHE.get("mtime") == mtime:
        return _CACHE.get("cal")
    cal = load(path)
    _CACHE.update(path=path, mtime=mtime, cal=cal)
    return cal


# ---------------------------------------------------------------------------
# predicted-vs-measured joins
# ---------------------------------------------------------------------------


def _measured_wall_s(measured: Dict[str, Any]) -> Optional[float]:
    w = (measured.get("wall_s") or {}).get("p50")
    return float(w) if isinstance(w, (int, float)) and w > 0 else None


def _booked_comm_bytes(measured: Dict[str, Any]) -> Optional[float]:
    total = 0.0
    seen = False
    # by_verb_dtype is the finer booking; fall back to the axis rollup
    for key in ("comm_bytes_by_verb_dtype", "comm_bytes_by_axis"):
        table = measured.get(key)
        if isinstance(table, dict) and table:
            for row in table.values():
                if isinstance(row, dict) and isinstance(
                        row.get("bytes"), (int, float)):
                    total += row["bytes"]
                    seen = True
            break
    return total if seen else None


def join(record: Dict[str, Any]) -> Dict[str, Any]:
    """Per-record error ratios: each is measured / predicted, so 1.0 is a
    perfect model, 2.0 means the measurement is twice the prediction.
    Ratios are emitted only when both sides carry the signal."""
    measured = record.get("measured") or {}
    predicted = record.get("predicted") or {}
    out: Dict[str, Any] = {"fingerprint": record.get("fingerprint"),
                           "run": record.get("run"), "ts": record.get("ts")}

    # hbm: measured peak live bytes vs the static-hbm pass estimate
    peak = (measured.get("hbm") or {}).get("peak_bytes")
    est = predicted.get("hbm_peak_bytes")
    if isinstance(peak, (int, float)) and isinstance(est, (int, float)) \
            and est > 0:
        out["hbm_ratio"] = round(peak / est, 4)

    # bubble: measured pipeline bubble fraction vs the analytic floor
    bub = ((measured.get("timeline") or {}).get("bubble_fraction")
           or {}).get("p50")
    floor = predicted.get("bubble_floor")
    if isinstance(bub, (int, float)) and isinstance(floor, (int, float)) \
            and floor > 0:
        out["bubble_ratio"] = round(bub / floor, 4)

    # comm: booked collective bytes (CommAccount tables riding the
    # journal) vs the static per-step census
    booked = _booked_comm_bytes(measured)
    static = predicted.get("comm_bytes_per_step")
    if booked is not None and isinstance(static, (int, float)) and static > 0:
        out["comm_ratio"] = round(booked / static, 4)

    # wall: measured p50 step seconds vs the modeled compute+wire seconds
    wall = _measured_wall_s(measured)
    modeled = predicted.get("modeled_step_s")
    if wall is not None and isinstance(modeled, (int, float)) and modeled > 0:
        out["wall_ratio"] = round(wall / modeled, 4)
    return out


def summarize(records: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Roll per-record joins up per fingerprint: median of each ratio
    plus the record count — the trend view ``ledger calibrate`` prints."""
    by_fp: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        if rec.get("kind") != "run":
            continue
        j = join(rec)
        by_fp.setdefault(str(j.get("fingerprint")), []).append(j)
    out: Dict[str, Any] = {}
    for fp, joins in by_fp.items():
        row: Dict[str, Any] = {"records": len(joins),
                               "run": joins[-1].get("run")}
        for key in ("hbm_ratio", "bubble_ratio", "comm_ratio", "wall_ratio"):
            med = _median([j.get(key) for j in joins
                           if isinstance(j.get(key), (int, float))])
            if med is not None:
                row[key] = round(med, 4)
        out[fp] = row
    return out


# ---------------------------------------------------------------------------
# fitting the effective peaks
# ---------------------------------------------------------------------------


def fit(records: Sequence[Dict[str, Any]],
        *, min_comm_frac: float = 0.05) -> Dict[str, Any]:
    """Fit effective peak constants from run records.

    - ``peak_flops``: the median achieved FLOP/s
      (``predicted.flops_per_step / measured wall p50``) — the ceiling
      under which the cost model's compute seconds equal the measured
      wall for compute-bound runs (the honest tunnel denominator,
      PERF_NOTES "71-78 TF/s sustained vs the datasheet").
    - ``peak_ici_bytes_per_sec``: the median of booked-or-predicted comm
      bytes over the non-compute residual of the wall (clamped to at
      least ``min_comm_frac`` of the wall so a compute-saturated record
      can't fit an infinite wire).
    - ``peak_dcn_bytes_per_sec``: the median achieved slow-tier wire
      bytes/s on two-tier pod runs — ``predicted.dcn_bytes_per_step``
      (the CommAccount DCN-tier census, parallel/hierarchy.py) over the
      measured exposed DCN seconds (``timeline.tiers.dcn_s`` p50, armed
      by ``journal.set_step_comm(dcn_bytes_per_step=...)``). An armed
      calibration file feeds this straight into ``tracing.dcn_spec``.
    - ``peak_hbm_bytes_per_sec``: the median achieved bytes/s when
      records carry ``predicted.bytes_per_step`` (jaxpr operand+result
      totals — a pre-fusion upper bound, flagged by the journal's
      ``mfu_method``).

    Returns the calibration dict (:func:`save`-ready) with ``n_records``
    per constant; constants without enough signal are omitted.
    """
    ach_flops: List[float] = []
    ach_ici: List[float] = []
    ach_dcn: List[float] = []
    ach_hbm: List[float] = []
    for rec in records:
        if rec.get("kind") != "run":
            continue
        measured = rec.get("measured") or {}
        predicted = rec.get("predicted") or {}
        wall = _measured_wall_s(measured)
        if wall is None:
            continue
        flops = predicted.get("flops_per_step")
        eff_f = None
        if isinstance(flops, (int, float)) and flops > 0:
            eff_f = flops / wall
            ach_flops.append(eff_f)
        nbytes = predicted.get("bytes_per_step")
        if isinstance(nbytes, (int, float)) and nbytes > 0:
            ach_hbm.append(nbytes / wall)
        comm = _booked_comm_bytes(measured)
        if comm is None:
            comm = predicted.get("comm_bytes_per_step")
        if isinstance(comm, (int, float)) and comm > 0:
            # attribute the non-compute residual of the wall to the wire;
            # the clamp keeps a compute-saturated step from dividing by ~0
            residual = wall
            if eff_f is not None and ach_flops:
                compute_s = flops / max(ach_flops[-1], 1e-30)
                residual = max(wall - compute_s, min_comm_frac * wall)
            ach_ici.append(comm / residual)
        # slow-tier wire: predicted DCN bytes over the MEASURED exposed
        # DCN seconds (the per-link-class anatomy stamp) — the direct
        # achieved-bandwidth read, no residual attribution needed
        dcn_bytes = predicted.get("dcn_bytes_per_step")
        dcn_s = (((measured.get("timeline") or {}).get("tiers") or {})
                 .get("dcn_s") or {}).get("p50")
        if isinstance(dcn_bytes, (int, float)) and dcn_bytes > 0 \
                and isinstance(dcn_s, (int, float)) and dcn_s > 0:
            ach_dcn.append(dcn_bytes / dcn_s)
    out: Dict[str, Any] = {"source": "calibrated",
                           "n_records": {}}
    f = _median(ach_flops)
    if f is not None:
        out["peak_flops"] = round(f, 1)
        out["n_records"]["peak_flops"] = len(ach_flops)
    i = _median(ach_ici)
    if i is not None:
        out["peak_ici_bytes_per_sec"] = round(i, 1)
        out["n_records"]["peak_ici_bytes_per_sec"] = len(ach_ici)
    d = _median(ach_dcn)
    if d is not None:
        out["peak_dcn_bytes_per_sec"] = round(d, 1)
        out["n_records"]["peak_dcn_bytes_per_sec"] = len(ach_dcn)
    h = _median(ach_hbm)
    if h is not None:
        out["peak_hbm_bytes_per_sec"] = round(h, 1)
        out["n_records"]["peak_hbm_bytes_per_sec"] = len(ach_hbm)
    return out
