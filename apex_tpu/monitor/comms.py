"""Collective accounting: named comm scopes + per-axis byte counters.

Every collective verb in ``parallel/collectives.py`` and every conjugate
TP collective in ``transformer/tensor_parallel/mappings.py`` runs under a
``jax.named_scope`` of the form ``comm:<verb>[<axis>]``. Two consumers:

1. **Trace-join attribution** (measured): the scope lands in HLO op_name
   metadata, so ``pyprof.measured_scope_seconds`` / ``_measured_join`` rows
   now carry per-axis comm time (``comm:psum[data]``, ``comm:ppermute[pipe]``,
   ...) exactly like the model's attention/mlp scopes — the per-stage timing
   telemetry MPMD pipeline work uses to find stragglers.
2. **Algorithmic byte counters** (traced): inside a
   :func:`comm_accounting` context, each traced collective call site adds
   its payload bytes to a :class:`CommAccount`, keyed by verb and axis.
   Like ``pyprof.per_scope_costs`` these are attribution shares at trace
   time — a call site inside ``lax.scan`` is counted once per trace, not
   per trip (document per-step multipliers yourself when scanning).

Host-side and allocation-free when no account is active: the only always-on
cost is the ``named_scope`` context, which exists at trace time only.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Tuple, Union

AxisNames = Union[str, Tuple[str, ...]]

# active accounts (innermost last). Plain module list: tracing is
# single-threaded per process; nested contexts both observe a call.
_ACTIVE: List["CommAccount"] = []

#: mesh axis names modeled as the slow inter-host DCN tier (the two-tier
#: topology of parallel/hierarchy.py). Everything else is ICI. A payload
#: whose axis label CONTAINS a DCN axis — including a "+"-joined tuple
#: label like "dcn+data", the flat-collective-spanning-tiers hazard — is
#: booked on the DCN tier: its wire crosses the slow links.
DCN_AXES = {"dcn"}


def register_dcn_axis(name: str) -> None:
    """Tag a mesh axis as riding the DCN tier (parallel/hierarchy.py's
    island axis registers itself; custom pod layouts add their own)."""
    DCN_AXES.add(str(name))


def axis_tier(label: AxisNames) -> str:
    """``"dcn"`` if any component of the (possibly "+"-joined) axis label
    is a registered DCN axis, else ``"ici"``."""
    parts = _axis_label(label).split("+")
    return "dcn" if any(p in DCN_AXES for p in parts) else "ici"


def _axis_label(axis: AxisNames) -> str:
    if isinstance(axis, (tuple, list)):
        return "+".join(str(a) for a in axis)
    return str(axis)


def _tree_bytes(tree: Any) -> Tuple[int, str]:
    """``(payload bytes, wire dtype)`` of a pytree of arrays/tracers
    (aval shape x itemsize). The dtype label is the leaves' common dtype
    ("mixed" when a multi-dtype tree rides one collective) — the wire-
    dtype dimension of the accounting, so an int8-quantized payload and
    its fp32 scale side-channel tally as separate rows."""
    import jax
    import numpy as np

    total = 0
    dtypes = set()
    for leaf in jax.tree.leaves(tree):
        try:
            size = int(np.prod(leaf.shape)) if leaf.shape else 1
            total += size * np.dtype(leaf.dtype).itemsize
            dtypes.add(str(np.dtype(leaf.dtype)))
        except Exception:  # noqa: BLE001 - tokens, python scalars
            continue
    if not dtypes:
        dtype = "none"
    elif len(dtypes) == 1:
        dtype = dtypes.pop()
    else:
        dtype = "mixed"
    return total, dtype


class CommAccount:
    """Byte/count tallies per (verb, axis, wire dtype) collective call
    site."""

    def __init__(self):
        self.records: List[Dict[str, Any]] = []

    def add(self, verb: str, axis: str, nbytes: int, dtype: str = "none"):
        self.records.append({"verb": verb, "axis": axis, "bytes": nbytes,
                             "dtype": dtype})

    def _group(self, key: str) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for r in self.records:
            row = out.setdefault(r[key], {"bytes": 0, "calls": 0})
            row["bytes"] += r["bytes"]
            row["calls"] += 1
        return out

    def by_axis(self) -> Dict[str, Dict[str, int]]:
        """``{axis: {"bytes", "calls"}}`` — the dp/tp/pp/cp attribution."""
        return self._group("axis")

    def by_verb(self) -> Dict[str, Dict[str, int]]:
        return self._group("verb")

    def by_verb_dtype(self, axis: Optional[str] = None
                      ) -> Dict[str, Dict[str, int]]:
        """``{"<verb>[<dtype>]": {"bytes", "calls"}}`` — the wire-dtype
        rollup: a quantized reduce books its int8 payload and its fp32
        scale side-channel as distinct rows, so the 1/4-bytes compression
        claim (and the side-channel's cost) read straight off the table.
        ``axis`` restricts to one mesh axis (the evidence harnesses' view
        of the data-axis wire)."""
        out: Dict[str, Dict[str, int]] = {}
        for r in self.records:
            if axis is not None and r["axis"] != axis:
                continue
            key = f"{r['verb']}[{r.get('dtype', 'none')}]"
            row = out.setdefault(key, {"bytes": 0, "calls": 0})
            row["bytes"] += r["bytes"]
            row["calls"] += 1
        return out

    def by_tier(self) -> Dict[str, Dict[str, int]]:
        """``{"ici"|"dcn": {"bytes", "calls"}}`` — the link-class rollup
        of the two-tier topology (parallel/hierarchy.py): every record
        whose axis label touches a registered DCN axis books on the slow
        tier. The per-tier wire-byte claims of the pod evidence read
        straight off this table."""
        out: Dict[str, Dict[str, int]] = {}
        for r in self.records:
            row = out.setdefault(axis_tier(r["axis"]),
                                 {"bytes": 0, "calls": 0})
            row["bytes"] += r["bytes"]
            row["calls"] += 1
        return out

    def total_bytes(self) -> int:
        return sum(r["bytes"] for r in self.records)

    def summary(self) -> Dict[str, Any]:
        return {"total_bytes": self.total_bytes(),
                "by_axis": self.by_axis(), "by_verb": self.by_verb(),
                "by_verb_dtype": self.by_verb_dtype(),
                "by_tier": self.by_tier()}


@contextlib.contextmanager
def comm_accounting():
    """Collect collective payload bytes for everything traced inside.

    >>> with comm_accounting() as acct:
    ...     jax.make_jaxpr(train_step)(params, opt_state, toks, tgts)
    >>> acct.by_axis()   # {"data": {"bytes": ..., "calls": ...}, ...}
    """
    acct = CommAccount()
    _ACTIVE.append(acct)
    try:
        yield acct
    finally:
        _ACTIVE.remove(acct)


def collective_scope(verb: str, axis: AxisNames, tree: Any):
    """Scope a collective call site: named range + byte accounting.

    Returns a context manager to wrap the ``lax`` collective in. The scope
    name ``comm:<verb>[<axis>]`` is the trace-join key; byte tallies go to
    every active :func:`comm_accounting` context.
    """
    import jax

    label = _axis_label(axis)
    if _ACTIVE:
        nbytes, dtype = _tree_bytes(tree)
        for acct in _ACTIVE:
            acct.add(verb, label, nbytes, dtype)
    try:
        # hang-attribution breadcrumb (monitor/flight.py): stamp the
        # scope being ENTERED so a process wedged inside it dies with
        # its name in the structured heartbeat (watchdog kill report).
        # This call site runs at TRACE time (and in the eager per-tick
        # drives), so it attributes compile-/trace-time and eager-drive
        # hangs; a COMPILED step wedged on-device is attributed by the
        # fetch-point breadcrumbs instead. A dict assignment when no
        # flight/heartbeat consumer is armed; the compiled program is
        # untouched either way.
        from apex_tpu.monitor import flight as _flight

        _flight.breadcrumb(f"comm:{verb}[{label}]")
    except Exception:  # noqa: BLE001 - telemetry must not kill tracing
        pass
    return jax.named_scope(f"comm:{verb}[{label}]")
