"""Static & dynamic loss scalers, legacy names
(reference: apex/fp16_utils/loss_scaler.py:10-45,47+).

Both are thin views over the amp ``LossScaler`` pytree so legacy code and amp
code share one state machine. ``LossScaler`` here is the *static* scaler (the
reference's class of the same name); ``DynamicLossScaler`` mirrors the
2^16-init / x2-window-2000 / /2-on-overflow schedule.
"""

from __future__ import annotations

from apex_tpu.amp.scaler import LossScaler as _AmpScaler


def LossScaler(scale: float = 1.0) -> _AmpScaler:
    """Static scaler (loss_scaler.py:10-45): fixed ``scale``, never updates."""
    return _AmpScaler.create(loss_scale=float(scale))


def DynamicLossScaler(
    init_scale: float = 2.0 ** 32,
    scale_factor: float = 2.0,
    scale_window: int = 1000,
) -> _AmpScaler:
    """Dynamic scaler with the legacy defaults (loss_scaler.py:47+:
    init 2^32, window 1000 — *not* the amp defaults of 2^16/2000)."""
    return _AmpScaler.create(
        loss_scale="dynamic",
        init_scale=init_scale,
        scale_factor=scale_factor,
        scale_window=scale_window,
    )
