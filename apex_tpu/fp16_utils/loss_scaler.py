"""Static & dynamic loss scalers, legacy names
(reference: apex/fp16_utils/loss_scaler.py:10-45,47+).

Both are thin views over the amp ``LossScaler`` pytree so legacy code and amp
code share one state machine, with the *legacy* defaults: the dynamic scaler
starts at 2^32 with a growth window of 1000 and no growth cap (the legacy
class has none — vs amp's 2^16 / 2000 / 2^24-cap defaults).
"""

from __future__ import annotations

from apex_tpu.amp.scaler import LossScaler as _AmpScaler


def LossScaler(scale: float = 1.0) -> _AmpScaler:
    """Static scaler (loss_scaler.py:10-45): fixed ``scale``, never updates."""
    return _AmpScaler.create(loss_scale=float(scale))


def DynamicLossScaler(
    init_scale: float = 2.0 ** 32,
    scale_factor: float = 2.0,
    scale_window: int = 1000,
) -> _AmpScaler:
    """Dynamic scaler with the legacy defaults (loss_scaler.py:47+)."""
    return _AmpScaler.create(
        loss_scale="dynamic",
        init_scale=init_scale,
        scale_factor=scale_factor,
        scale_window=scale_window,
        # legacy scaler has no growth cap; never clamp below the init scale
        max_loss_scale=float("inf"),
    )
