"""FP16_Optimizer — the legacy master-weight wrapper
(reference: apex/fp16_utils/fp16_optimizer.py:13-551).

The reference wraps a torch optimizer: it clones fp16 params into fp32
masters, patches ``backward()`` to scale the loss, unscales grads into the
masters, optionally clips them (``clip_master_grads``), steps in fp32, and
copies masters back to the fp16 model params; dynamic loss scaling skips
steps on overflow.

Functional translation: the wrapper owns an inner ``ClassOptimizer``/optax
transform; its state is ``(inner, master, scaler)``; ``step`` performs
unscale → clip → ``lax.cond``-guarded update → master→model copy-out, and
``state_dict``/``load_state_dict`` round-trip everything
(fp16_optimizer.py:209-271).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import optax

from apex_tpu.amp.scaler import LossScaler as _AmpScaler
from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler
from apex_tpu.fp16_utils.fp16util import (
    master_params_to_model_params,
    prep_param_lists,
)
from apex_tpu.ops.multi_tensor import tree_clip_by_global_norm, tree_l2norm
from apex_tpu.optimizers._common import ClassOptimizer


class FP16OptState(NamedTuple):
    inner: Any
    master: Any
    scaler: _AmpScaler


class FP16_Optimizer:
    """Drop-in legacy wrapper (fp16_optimizer.py:13-130 constructor surface:
    ``static_loss_scale``, ``dynamic_loss_scale``, ``dynamic_loss_args``,
    ``verbose`` is dropped).

    >>> opt = FP16_Optimizer(FusedAdam(lr=1e-3), dynamic_loss_scale=True)
    >>> state = opt.init(bf16_params)
    >>> scaled = opt.scale_loss(loss, state)        # "backward(loss)"
    >>> params, state, info = opt.step(state, params, scaled_grads,
    ...                                max_norm=1.0)  # clip_master_grads
    """

    def __init__(
        self,
        optimizer: Union[optax.GradientTransformation, ClassOptimizer],
        static_loss_scale: float = 1.0,
        dynamic_loss_scale: bool = False,
        dynamic_loss_args: Optional[dict] = None,
    ):
        self.inner = (
            optimizer.transform if isinstance(optimizer, ClassOptimizer) else optimizer
        )
        self.dynamic = dynamic_loss_scale
        if dynamic_loss_scale:
            kwargs = dict(dynamic_loss_args or {})
            self._mk_scaler = lambda: DynamicLossScaler(**kwargs)
        else:
            self._mk_scaler = lambda: LossScaler(static_loss_scale)

    def init(self, model_params) -> FP16OptState:
        _, master = prep_param_lists(model_params)
        return FP16OptState(
            inner=self.inner.init(master),
            master=master,
            scaler=self._mk_scaler(),
        )

    def scale_loss(self, loss: jax.Array, state: FP16OptState) -> jax.Array:
        """The ``optimizer.backward(loss)`` scaling half
        (fp16_optimizer.py:326-388): scale the loss, let the caller autodiff."""
        return state.scaler.scale(loss)

    def clip_master_grads(self, grads32, max_norm: float) -> Tuple[Any, jax.Array]:
        """Global-norm clip over the unscaled master grads
        (``clip_master_grads``, fp16_optimizer.py:274-292). Returns
        ``(clipped, total_norm)``."""
        return tree_clip_by_global_norm(grads32, max_norm)

    def step(
        self,
        state: FP16OptState,
        model_params,
        scaled_grads,
        max_norm: Optional[float] = None,
    ):
        """unscale → (clip) → cond-guarded fp32 update → copy-out
        (``step``, fp16_optimizer.py:294-324). Returns
        ``(new_model_params, new_state, info)`` with
        ``info = {overflow, loss_scale, grad_norm}``."""
        grads32, found_inf = state.scaler.unscale(scaled_grads, out_dtype=jnp.float32)
        if max_norm is not None:
            grads32, grad_norm = self.clip_master_grads(grads32, max_norm)
        else:
            grad_norm = tree_l2norm(grads32)

        def _do(operand):
            master, inner = operand
            updates, new_inner = self.inner.update(grads32, inner, master)
            return optax.apply_updates(master, updates), new_inner

        if self.dynamic:
            new_master, new_inner = jax.lax.cond(
                found_inf, lambda o: o, _do, (state.master, state.inner)
            )
        else:
            # legacy static scaler never skips: the step proceeds and any
            # non-finites surface in the params (reference LossScaler has no
            # overflow machinery, loss_scaler.py:10-45) — found_inf is still
            # reported in info for callers that want to react.
            new_master, new_inner = _do((state.master, state.inner))
        new_model = master_params_to_model_params(new_master, model_params)
        new_scaler = state.scaler.update(found_inf)
        info = {
            "overflow": found_inf,
            "loss_scale": new_scaler.loss_scale,
            "grad_norm": grad_norm,
        }
        return new_model, FP16OptState(new_inner, new_master, new_scaler), info

    # -- checkpointing (fp16_optimizer.py:209-271) --------------------------
    def state_dict(self, state: FP16OptState):
        return {
            "inner": state.inner,
            "master": state.master,
            "scaler": state.scaler.state_dict(),
        }

    def load_state_dict(self, state: FP16OptState, payload) -> FP16OptState:
        """Restores masters/inner/scaler. Like the reference, the inner state
        tree structure must match the wrapped optimizer's."""
        return FP16OptState(
            inner=jax.tree.map(lambda _, v: jnp.asarray(v), state.inner, payload["inner"]),
            master=jax.tree.map(lambda _, v: jnp.asarray(v), state.master, payload["master"]),
            scaler=state.scaler.load_state_dict(payload["scaler"]),
        )
