"""Conversion helpers (reference: apex/fp16_utils/fp16util.py:35-175).

The reference mutates torch modules in place (``network.half()``, master
``Parameter`` clones); the functional equivalents transform pytrees and
return new trees.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _is_float(a) -> bool:
    return hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)


def tofp16(params):
    """Cast all floating leaves to fp16 (``tofp16``/``network.half()``,
    fp16util.py:35-42). On TPU prefer bf16 via ``convert_network``."""
    return jax.tree.map(
        lambda a: a.astype(jnp.float16) if _is_float(a) else a, params
    )


def convert_network(params, dtype=jnp.bfloat16, keep_norms_fp32: bool = True):
    """Cast a network's params, optionally keeping norm-layer params fp32
    (``convert_network`` skips _BatchNorm modules, fp16util.py:44-58).

    Delegates to :func:`apex_tpu.precision.cast_floats` so norm detection has
    a single home."""
    from apex_tpu.precision import cast_floats

    return cast_floats(params, dtype, keep_norms_fp32=keep_norms_fp32)


def prep_param_lists(params):
    """``(model_params, master_params)``: fp32 master copies of the model tree
    (``prep_param_lists``, fp16util.py:100-126 — without the flatten option;
    XLA fuses the update sweep regardless of memory layout)."""
    master = jax.tree.map(
        lambda a: a.astype(jnp.float32) if _is_float(a) else a, params
    )
    return params, master


def model_grads_to_master_grads(model_grads):
    """Copy model (possibly half) grads into fp32 master grads
    (fp16util.py:128-150)."""
    return jax.tree.map(
        lambda g: g.astype(jnp.float32) if _is_float(g) else g, model_grads
    )


def master_params_to_model_params(master_params, model_params):
    """Cast updated masters back into the model dtypes (fp16util.py:152-175)."""
    return jax.tree.map(
        lambda m, p: m.astype(p.dtype) if _is_float(p) else m,
        master_params, model_params,
    )
