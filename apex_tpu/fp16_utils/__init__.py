"""Legacy manual mixed-precision API (reference: apex/fp16_utils/).

The pre-amp surface the reference keeps for backward compatibility:
``FP16_Optimizer`` (fp16_optimizer.py:13-551), static/dynamic ``LossScaler``
(loss_scaler.py), and the conversion helpers (fp16util.py:35-175). New code
should use ``apex_tpu.amp``; this package preserves the old names and
semantics for users migrating reference scripts.
"""

from apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer  # noqa: F401
from apex_tpu.fp16_utils.fp16util import (  # noqa: F401
    convert_network,
    master_params_to_model_params,
    model_grads_to_master_grads,
    prep_param_lists,
    tofp16,
)
from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler  # noqa: F401
