"""Megatron-style BERT (reference: apex/transformer/testing/standalone_bert.py).

The reference vendors a Megatron BERT (BertModel + BertLMHead +
post_language_model_processing, standalone_bert.py:35-216) as the second test
vehicle for its transformer framework; the BASELINE.md config-3 workload is
BERT-large pretraining with FusedLAMB + FusedLayerNorm. This is the TPU-native
counterpart, sharing the GPT model's structural choices (stacked layer params
driven by ``lax.scan``, `jax.checkpoint` remat, serial==sharded code path) with
BERT's own semantics:

- bidirectional attention under a **padding mask** built from
  ``attention_mask`` (bert_extended_attention_mask, standalone_bert.py:10-23 —
  additive -10000 bias instead of masked_fill);
- word + learned-position + **tokentype** embeddings, then embedding LN +
  dropout (Megatron Embedding with tokentype, standalone_gpt.py:236-420);
- **post-LN** encoder blocks (residual add *then* LayerNorm);
- MLM head: dense+gelu+LN then the tied vocab-parallel decode with bias
  (BertLMHead, standalone_bert.py:35-74);
- optional binary (NSP) head on the pooled [CLS] (Pooler + binary head,
  post_language_model_processing, standalone_bert.py:76-98);
- masked-LM loss = vocab-parallel cross entropy over masked positions only
  (loss-mask weighting, the lm_loss_/loss_mask contract of the reference's
  bert fwd_step, run_bert_minimal_test.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.models._transformer import SegmentMask, TransformerBase
from apex_tpu.parallel.mesh import AXIS_MODEL
from apex_tpu.transformer import tensor_parallel as tp

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    """BERT hyperparameters (bert-large defaults; testing/arguments.py)."""

    vocab_size: int = 30592  # 30522 padded to a TP-friendly multiple
    hidden_size: int = 1024
    num_layers: int = 24
    num_attention_heads: int = 16
    max_seq_len: int = 512
    type_vocab_size: int = 2
    ffn_hidden_size: Optional[int] = None
    axis: Optional[str] = AXIS_MODEL
    # Megatron-style sequence parallelism on the TP axis (see
    # GPTConfig.sequence_parallel): decomposed TP collectives +
    # sequence-sharded LN/dropout/residual regions; the MLM head gathers
    # the sequence back at entry (the [CLS] pooler and the tied decode see
    # the full sequence). Ignored when axis is None.
    sequence_parallel: bool = False
    # Quantized wire dtype ("int8" | "e5m2") for the sequence-parallel
    # activation conjugates (requires sequence_parallel=True) — see
    # GPTConfig.activation_comm_dtype. None = exact wire.
    activation_comm_dtype: Optional[str] = None
    params_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    hidden_dropout: float = 0.1
    init_method_std: float = 0.02
    remat: bool = True
    add_binary_head: bool = True
    attention_impl: str = "auto"
    # symmetric sliding-window attention (bidirectional band
    # [p-w+1, p+w-1]; flash_attention `window` semantics). None = full.
    attention_window: Optional[int] = None
    # unrolled layer drive (same stacked params, static per-layer slices):
    # avoids the layer scan's dynamic-update-slice grad stacking — see
    # GPTConfig.unroll_layers and PERF_NOTES r5
    unroll_layers: bool = False
    # ZeRO-3 gather prefetch depth on the unrolled path (double-buffered
    # per-layer chunk all-gathers — see GPTConfig.zero3_prefetch); the
    # prefetch drive is dense/dropout-off only, so BERT runs it through
    # the pipelined ZeRO-3 step, not the SegmentMask attention path
    zero3_prefetch: int = 0
    # sequence (context) parallelism over this mesh axis — the shared
    # TransformerBase._attend ring/Ulysses path (bidirectional here).
    # Padding attention_masks work: they become segment ids whose kv
    # shards ride the K/V ring (SegmentMask, models/_transformer.py), and
    # the NSP pooler replicates the global [CLS] across shards
    context_axis: Optional[str] = None
    sequence_parallel_impl: str = "ring"  # 'ring' | 'ulysses'

    @property
    def ffn(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def extended_attention_mask(attention_mask: jax.Array) -> jax.Array:
    """(b, s) 1/0 padding mask → additive (b, 1, 1, s) bias
    (bert_extended_attention_mask, standalone_bert.py:10-23)."""
    bias = (1.0 - attention_mask.astype(jnp.float32)) * -10000.0
    return bias[:, None, None, :]


class BertModel(TransformerBase):
    """Functional BERT with TP-sharded params.

    ``apply(params, tokens, attention_mask, tokentype_ids=..., ...)`` returns
    ``(lm_logits, binary_logits)``; ``loss(...)`` the masked-LM (+NSP) loss.
    ``embed`` / ``run_layers`` / ``head`` expose pipeline stage boundaries
    like GPTModel. Shared transformer plumbing lives in TransformerBase
    (models/_transformer); BERT keeps post-LN blocks and a padding-mask bias.
    """

    causal = False

    # -- parameters ---------------------------------------------------------

    def init(self, key: jax.Array) -> Params:
        c = self.cfg
        keys = jax.random.split(key, 8)
        pos = tp.scaled_normal(c.init_method_std)(
            keys[1], (c.max_seq_len, c.hidden_size), c.params_dtype)
        tokentype = tp.scaled_normal(c.init_method_std)(
            keys[2], (c.type_vocab_size, c.hidden_size), c.params_dtype)

        layers = self.init_layer_stack(keys[3])

        params = {
            "embedding": self.embedding.init(keys[0]),
            "position": pos,
            "tokentype": tokentype,
            "ln_emb": self._ln_init(),
            "layers": layers,
            # BertLMHead (standalone_bert.py:46-74): dense+gelu+LN, then the
            # tied decode plus a vocab-sharded output bias.
            "lm_dense": self._dense_init(keys[4], c.hidden_size, c.hidden_size),
            "lm_ln": self._ln_init(),
            "lm_bias": jnp.zeros((c.vocab_size,), c.params_dtype),
        }
        if c.add_binary_head:
            params["pooler"] = self._dense_init(keys[5], c.hidden_size, c.hidden_size)
            params["binary_head"] = self._dense_init(keys[6], c.hidden_size, 2)
        return params

    def specs(self) -> Params:
        c = self.cfg
        ln = {"scale": P(), "bias": P()}
        dense = {"kernel": P(), "bias": P()}

        specs = {
            "embedding": self.embedding.specs(),
            "position": P(),
            "tokentype": P(),
            "ln_emb": ln,
            "layers": self.layer_stack_specs(),
            "lm_dense": dense,
            "lm_ln": ln,
            "lm_bias": P(c.axis) if c.axis else P(),
        }
        if c.add_binary_head:
            specs["pooler"] = dense
            specs["binary_head"] = dense
        return specs

    # -- stages -------------------------------------------------------------

    def embed(
        self,
        params: Params,
        tokens: jax.Array,
        tokentype_ids: Optional[jax.Array] = None,
        dropout_key: Optional[jax.Array] = None,
    ) -> jax.Array:
        c = self.cfg
        with jax.named_scope("embed"):
            h = self.embedding.apply(params["embedding"], tokens)
            # h.shape[1] is the sequence-parallel shard length under SP
            # (the embedding reduce-scattered); positions/tokentypes add
            # after the closing collective, never to the partial sums
            h = h + self._positions(params["position"], h.shape[1])
            if tokentype_ids is not None:
                if self._sp:
                    s_local = h.shape[1]
                    tokentype_ids = lax.dynamic_slice_in_dim(
                        tokentype_ids, lax.axis_index(c.axis) * s_local,
                        s_local, axis=1)
                h = h + jnp.take(self._sp_param(params["tokentype"]),
                                 tokentype_ids, axis=0)
            h = self._ln(params["ln_emb"], h.astype(c.compute_dtype))
            return self._dropout(h, dropout_key).astype(c.compute_dtype)

    def _layer(self, p: Params, h: jax.Array, key, bias=None) -> jax.Array:
        """Post-LN block: LN(residual + sublayer(h))."""
        k1, k2 = (None, None) if key is None else tuple(jax.random.split(key))
        h = self._ln(p["ln1"], h + self._dropout(self._attention(p, h, bias), k1))
        h = self._ln(p["ln2"], h + self._dropout(self._mlp(p, h), k2))
        return h

    def head(
        self,
        params: Params,
        h: jax.Array,
        masked_lm_labels: Optional[jax.Array] = None,
    ):
        """MLM decode (+ binary logits). With labels: per-token vocab-parallel
        CE (post_language_model_processing, standalone_bert.py:76-98)."""
        c = self.cfg
        with jax.named_scope("head"):
            if self._sp:
                # close the sequence-sharded region before anything reads
                # global positions (the [CLS] pooler) or the tied decode.
                # Everything downstream — lm_dense, lm_ln, the copy_to'd
                # decode, the CE psums — is REPLICATED across TP ranks, so
                # the gather's adjoint is a plain slice of the replicated
                # cotangent (tensor_parallel_output_grad=False); a
                # reduce-scatter there would double-count what copy_to's
                # backward psum already summed.
                h = tp.gather_from_sequence_parallel_region(
                    h, c.axis, False, self._acd)
            binary_logits = None
            if c.add_binary_head:
                cls = h[:, 0]
                if c.context_axis is not None:
                    # The global [CLS] (global position 0) lives on rank 0's
                    # shard; replicate it with a BARE psum of the rank-0-
                    # masked slice. Gradient bookkeeping: under
                    # check_vma=False psum transposes to psum, so rank 0's
                    # h[:, 0] cotangent arrives ×axis_size while other
                    # ranks get 0 — exactly cancelled by the pmean-over-
                    # context gradient reduction for replicated params
                    # (allreduce_gradients_by_spec / the CP test harness),
                    # the same bookkeeping as the ×n LM term in loss().
                    rank = lax.axis_index(c.context_axis)
                    cls = lax.psum(
                        jnp.where(rank == 0, cls, jnp.zeros_like(cls)),
                        c.context_axis)
                pooled = jnp.tanh(self._dense(params["pooler"], cls))
                binary_logits = self._dense(params["binary_head"],
                                            pooled.astype(jnp.float32))
            g = jax.nn.gelu(self._dense(params["lm_dense"], h))
            # past the head gather: replicated region, no γβ grad wrap
            g = self._ln(params["lm_ln"], g, sequence_region=False)
            if c.axis is not None:
                g = tp.copy_to_tensor_model_parallel_region(g, c.axis)
            wte = params["embedding"]["embedding"].astype(g.dtype)  # (V/tp, H)
            logits = (jnp.einsum("bsh,vh->bsv", g, wte)
                      + params["lm_bias"].astype(g.dtype))
            if masked_lm_labels is None:
                return logits, binary_logits
            lm_loss = tp.vocab_parallel_cross_entropy(
                logits, masked_lm_labels, axis=c.axis)
            return lm_loss, binary_logits

    def apply(
        self,
        params: Params,
        tokens: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        tokentype_ids: Optional[jax.Array] = None,
        masked_lm_labels: Optional[jax.Array] = None,
        dropout_key: Optional[jax.Array] = None,
        layer_chunk_meta=None,
    ):
        if attention_mask is None:
            bias = None
        elif self.cfg.context_axis is not None:
            # Under sequence sharding the padding mask becomes SEGMENT IDS
            # (valid=1, pad=0 with pad_id=0): the kv-id shards ride the
            # K/V ring, so no (sq, SK) bias ever materializes. Same
            # function as the additive -10000 bias for every position the
            # loss can see: padded KEYS are never attended either way, and
            # padded query rows (output 0 here vs a normal mix under the
            # bias) are exactly the rows loss_mask zeroes.
            seg = attention_mask.astype(jnp.int32)
            bias = SegmentMask(q_seg=seg, kv_seg=seg, pad_id=0)
        else:
            bias = extended_attention_mask(attention_mask)
        k_emb = k_layers = None
        if dropout_key is not None:
            k_emb, k_layers = jax.random.split(dropout_key)
        h = self.embed(params, tokens, tokentype_ids, k_emb)
        # layer_chunk_meta = the ZeRO-3 fully-sharded drive (per-layer JIT
        # weight gather, models/_transformer.run_layers chunk_meta)
        h = self.run_layers(params["layers"], h, bias, k_layers,
                            chunk_meta=layer_chunk_meta)
        return self.head(params, h, masked_lm_labels)

    def loss(
        self,
        params: Params,
        tokens: jax.Array,
        attention_mask: jax.Array,
        loss_mask: jax.Array,
        masked_lm_labels: jax.Array,
        nsp_labels: Optional[jax.Array] = None,
        tokentype_ids: Optional[jax.Array] = None,
        dropout_key: Optional[jax.Array] = None,
        layer_chunk_meta=None,
    ) -> jax.Array:
        """lm_loss averaged over masked positions (+ NSP CE), the bert
        fwd_step contract (run_bert_minimal_test.py loss_func).

        Under ``context_axis`` the return is the LOCAL term whose
        pmean-over-context equals the global loss (the repo's local-loss +
        pmean-gradients convention): the masked mean normalizes by the
        GLOBAL weight sum — a per-shard mean would mis-weight shards with
        unequal masked-token counts — scaled by axis_size so the harness's
        pmean recovers sum/W exactly."""
        c = self.cfg
        lm_loss, binary_logits = self.apply(
            params, tokens, attention_mask, tokentype_ids,
            masked_lm_labels, dropout_key,
            layer_chunk_meta=layer_chunk_meta)
        w = loss_mask.astype(jnp.float32)
        local = jnp.sum(lm_loss * w)
        if c.context_axis is not None:
            n = lax.axis_size(c.context_axis)
            total_w = lax.psum(jnp.sum(w), c.context_axis)
            # total_w has no parameter dependence: safe outside the grad
            # path (stop_gradient makes that explicit)
            loss = local * n / jnp.maximum(lax.stop_gradient(total_w), 1.0)
        else:
            loss = local / jnp.maximum(jnp.sum(w), 1.0)
        if nsp_labels is not None and binary_logits is not None:
            logp = jax.nn.log_softmax(binary_logits.astype(jnp.float32))
            nsp = -jnp.mean(jnp.take_along_axis(logp, nsp_labels[:, None], axis=1))
            loss = loss + nsp
        return loss
