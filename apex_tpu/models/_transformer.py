"""Shared transformer backbone for the model zoo (GPT, BERT).

The reference's standalone_gpt.py and standalone_bert.py share Megatron's
ParallelMLP/ParallelAttention/ParallelTransformer internals; here the shared
plumbing lives in :class:`TransformerBase` and the models keep only their own
semantics (pre-LN causal LM vs post-LN masked LM, heads, losses).

Both models use the same per-layer parameter tree
``{ln1, ln2, qkv, proj, fc1, fc2}`` stacked on a leading ``num_layers`` dim
and driven by ``lax.scan`` (compile time O(1) in depth, natural pipeline-stage
slicing); only ``_layer`` — where LN sits relative to the residual — differs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.ops.layer_norm import layer_norm as fused_layer_norm_op
from apex_tpu.transformer import tensor_parallel as tp
from apex_tpu.utils.nn import inverted_dropout

#: the ONE rejection text for ``zero3_prefetch`` without unrolled layers —
#: shared by the trace-time check here (run_layers) and the build-time
#: check in ``transformer.amp.build_zero_train_step`` so harness and audit
#: reject with identical words (tests pin the equality)
ZERO3_PREFETCH_NEEDS_UNROLL = (
    "zero3_prefetch needs unroll_layers=True: the double-buffered gather "
    "schedule is a static unrolled structure (a lax.scan has one gather "
    "call site to prefetch around)")

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class SegmentMask:
    """Attention masking by SEGMENT IDS instead of an additive bias.

    Flows through the same ``bias`` channel as additive masks
    (run_layers → _layer → _attention → _attend) but reaches the flash
    kernel's segment-id path — which, unlike a dense bias, works under
    sequence/context parallelism: the per-shard kv-id slices rotate around
    the ring with their K/V shard (transformer/ring.py). This is how BERT
    padding masks (bert_extended_attention_mask,
    standalone_bert.py:10-23) are expressed under ``context_axis``
    (VERDICT r3 ask #4).

    ``q_seg``/``kv_seg``: ``(b, s)`` int arrays (LOCAL shards under CP);
    keys with id ``pad_id`` are never attended and fully-padded query rows
    output exactly 0.
    """

    q_seg: jax.Array
    kv_seg: jax.Array
    pad_id: Optional[int] = None


def apply_rope(x: jax.Array, positions: jax.Array,
               theta: float = 10000.0) -> jax.Array:
    """Rotary position embedding (split-half / NeoX convention) on
    ``(b, nh, s, d)`` with explicit GLOBAL ``positions`` of shape ``(s,)``.

    Scores become functions of relative distance only —
    ``rope(q, p)·rope(k, p') == rope(q, p+s)·rope(k, p'+s)`` (unit-tested)
    — so the per-shard global positions make it exact under ring/Ulysses
    context parallelism, and no position table exists at all: at 1M
    tokens the learned table alone is ~3.75 GB of params+optimizer state.
    Beyond-reference capability (the reference's GPT is learned-position
    only, standalone_gpt.py embeddings)."""
    return _rope_rotate(x, positions, theta, batched=False)


def apply_rope_at(x: jax.Array, positions: jax.Array,
                  theta: float = 10000.0) -> jax.Array:
    """:func:`apply_rope` with PER-SEQUENCE positions: ``x`` is
    ``(b, nh, s, d)`` and ``positions`` is ``(b, s)`` — the decode-tick
    form, where every serving slot sits at its own context position. One
    shared angle/rotation body (:func:`_rope_rotate`), so a decoded
    token's rotation matches the training forward's bit for bit at equal
    position by construction."""
    return _rope_rotate(x, positions, theta, batched=True)


def _rope_rotate(x, positions, theta, *, batched):
    """Shared rope body: angles from the K-split reduction, then the
    split-half rotation. ``batched=False``: ``positions`` is ``(s,)``
    shared across the batch; ``True``: ``(b, s)`` per sequence (the
    angle tensor gains a leading batch dim, broadcast over heads).
    Per-element the two forms run the identical f32 op sequence — the
    serve equivalence gate rests on that."""
    import numpy as np

    d = x.shape[-1]
    half = d // 2
    # Angle precision at long context: pos · inv_freq in f32 carries a
    # relative 1e-7 error, which at pos = 1e6 is up to ~0.1 rad for the
    # highest frequency. Split the (exact, integer) position as
    # a·K + r and pre-reduce K·inv_freq modulo 2π in float64 at trace
    # time, so every f32 product stays small (≲ 3e3 rad → ≤ 3e-4 rad
    # error at 1M tokens).
    K = 2048
    inv64 = theta ** (-np.arange(half, dtype=np.float64) * 2.0 / d)
    kmod = jnp.asarray(np.mod(K * inv64, 2 * np.pi), jnp.float32)
    inv_freq = jnp.asarray(inv64, jnp.float32)
    a = (positions // K).astype(jnp.float32)[..., None]  # (s, 1) | (b, s, 1)
    r = (positions % K).astype(jnp.float32)[..., None]
    ang = a * kmod + r * inv_freq                        # (..., s, half)
    cos = jnp.cos(ang)[:, None] if batched else jnp.cos(ang)  # + head bcast
    sin = jnp.sin(ang)[:, None] if batched else jnp.sin(ang)
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _remat_policy(name: Optional[str]):
    """Selective activation-checkpoint policies (reference: the sharded
    activation buffer knob of tensor_parallel/random.py:45-76 — the
    memory/recompute dial, redesigned as jax.checkpoint policies):

    - None/"full": recompute everything (lowest memory);
    - "save_attn": save the flash-attention kernel outputs (tagged
      "flash_out"/"flash_lse" in ops/flash_attention._flash_fwd) so
      backward skips re-running the attention forward — the layer's most
      FLOP-expensive recompute — for O(b*h*s*d) extra memory per layer;
    - "dots": XLA's dots_with_no_batch_dims_saveable (save GEMM outputs).
    """
    if name in (None, "full"):
        return None
    if name == "save_attn":
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse")
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(f"unknown remat_policy {name!r}")


def _prefetched_zero3_drive(layer_fn, gather_fn, n: int, prefetch: int):
    """Software-pipelined (double-buffered) ZeRO-3 layer drive for the
    UNROLLED path: issue layer ``i+prefetch``'s chunk all-gather before
    layer ``i``'s compute, forward AND backward, so the gathers stand as
    structurally independent collectives ahead of the compute that hides
    them (the cross-replica weight-sharding layout of Xu et al. driven as
    an explicit prefetch schedule; tripwire:
    ``lint.trace.unprefetched_gather_hazards``).

    The serialized drive keeps each gather INSIDE the rematerialized scan
    body (run_layers ``chunk_meta``), which pins it to that body's
    schedule; this drive replaces ``jax.checkpoint`` with a
    ``jax.custom_vjp`` whose backward re-gathers each layer's weights
    (prefetched ``prefetch`` layers ahead of the reverse sweep) and
    rematerializes the layer forward under a fresh ``jax.vjp`` — identical
    remat semantics, same math (the gather's AD transpose still
    reduce-scatters that layer's grads on the spot, via ``jax.vjp`` of the
    same gather), but the gathered weights are never residuals: peak param
    residency is ``prefetch + 1`` layers plus chunks.

    ``layer_fn(p_full, h) -> h``; ``gather_fn(chunk_row) -> p_full``;
    ``n`` = layer count. Returns ``drive(chunks, h) -> h`` (chunks: the
    ``(L, k)`` per-row chunk stack).
    """
    pf = max(int(prefetch), 0)

    def _row(chunks, i):
        return jax.tree.map(lambda v: v[i], chunks)

    def _fwd(chunks, h):
        window = [gather_fn(_row(chunks, j)) for j in range(min(pf, n))]
        hs = []
        for i in range(n):
            if i + pf < n:
                # layer i+pf's gather is issued BEFORE layer i's compute
                window.append(gather_fn(_row(chunks, i + pf)))
            p = window.pop(0)
            hs.append(h)
            h = layer_fn(p, h)
        return h, (chunks, jnp.stack(hs))

    def _bwd(res, g):
        chunks, h_stack = res
        idxs = list(reversed(range(n)))
        window = [jax.vjp(gather_fn, _row(chunks, j))
                  for j in idxs[:min(pf, n)]]
        g_rows = [None] * n
        for pos, i in enumerate(idxs):
            if pos + pf < n:
                # the backward RE-gather for the layer prefetch steps
                # ahead of the current layer's VJP compute
                window.append(jax.vjp(gather_fn, _row(chunks, idxs[pos + pf])))
            p, gvjp = window.pop(0)
            _, lvjp = jax.vjp(layer_fn, p, h_stack[i])
            g_p, g = lvjp(g)
            (g_rows[i],) = gvjp(g_p)
        g_chunks = jax.tree.map(lambda *rows: jnp.stack(rows), *g_rows)
        return g_chunks, g

    @jax.custom_vjp
    def drive(chunks, h):
        return _fwd(chunks, h)[0]

    drive.defvjp(_fwd, _bwd)
    return drive


def stack_specs(spec_tree):
    """Prefix each PartitionSpec with the stacked (num_layers) dim."""
    return jax.tree.map(
        lambda s: P(None, *s), spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


class TransformerBase:
    """TP-sharded transformer plumbing shared by the model zoo.

    Subclasses define ``causal`` and ``_layer(p, h, key, bias)``, and their
    own ``init``/``specs``/``embed``/``head``. The config must provide the
    common fields (hidden_size, num_attention_heads, num_layers, ffn, axis,
    params_dtype, compute_dtype, hidden_dropout, init_method_std, remat,
    attention_impl, vocab_size).
    """

    causal: bool = True

    def __init__(self, config):
        self.cfg = c = config
        if c.hidden_size % c.num_attention_heads:
            raise ValueError("hidden_size must divide evenly into heads")
        # Megatron-style sequence parallelism over the TP axis
        # (cfg.sequence_parallel): the row-parallel forward psums decompose
        # into psum_scatter + a later pre-GEMM all-gather, and everything
        # between them (LN, dropout, residual) runs on (b, s/tp, h) shards.
        # Serial (axis=None) ignores the knob — one code path.
        self._sp = bool(getattr(c, "sequence_parallel", False)) and c.axis is not None
        # Quantized wire dtype of the sequence-parallel conjugates
        # (cfg.activation_comm_dtype -> the encode/decode pair of
        # parallel/quantize.py): activations quantize more safely than
        # grads — fresh values every step, per-shard scales bound the
        # error — so no residual state rides along (quantize.py module
        # doc). Only meaningful when the conjugates exist at all.
        self._acd = getattr(c, "activation_comm_dtype", None)
        if self._acd is not None:
            from apex_tpu.parallel.quantize import canon_wire_dtype

            self._acd = canon_wire_dtype(self._acd)
            if c.axis is None:
                # serial twin convention (same as sequence_parallel, which
                # is "ignored when axis is None"): the serial build of a
                # sharded config must run, one code path — there is no
                # wire to quantize
                self._acd = None
            elif not self._sp:
                raise ValueError(
                    "activation_comm_dtype requires sequence_parallel=True: "
                    "the quantized wire dtype rides the sequence-parallel "
                    "scatter/gather conjugates — plain-TP all-reduces have "
                    "no encode/decode seam")
        if self._sp:
            # seq % tp == 0 is a runtime property (the axis size lives in
            # the mesh), but when the mesh is already up we can fail HERE
            # with the knob named, instead of deep inside the embedding's
            # reduce-scatter with a bare divisibility error
            from apex_tpu.parallel import mesh as mesh_lib

            if mesh_lib.model_parallel_is_initialized():
                tp_size = mesh_lib.get_tensor_model_parallel_world_size()
                if tp_size > 1 and c.max_seq_len % tp_size:
                    raise ValueError(
                        f"sequence_parallel=True needs max_seq_len "
                        f"({c.max_seq_len}) divisible by the tensor-"
                        f"parallel size ({tp_size}): the embedding "
                        f"reduce-scatter shards the sequence tp ways")
        init = tp.scaled_normal(c.init_method_std)
        # Megatron scales output-layer init by 1/sqrt(2L)
        # (standalone_gpt.py scaled_init_method_normal).
        out_init = tp.scaled_normal(c.init_method_std / (2 * c.num_layers) ** 0.5)
        self._init = init
        self.embedding = tp.VocabParallelEmbedding(
            c.vocab_size, c.hidden_size, axis=c.axis,
            sequence_parallel=self._sp, comm_dtype=self._acd,
            params_dtype=c.params_dtype, init_method=init,
        )
        self.qkv = tp.ColumnParallelLinear(
            c.hidden_size, 3 * c.hidden_size, axis=c.axis, gather_output=False,
            sequence_parallel=self._sp, comm_dtype=self._acd,
            params_dtype=c.params_dtype, init_method=init,
        )
        self.proj = tp.RowParallelLinear(
            c.hidden_size, c.hidden_size, axis=c.axis, input_is_parallel=True,
            sequence_parallel=self._sp, comm_dtype=self._acd,
            params_dtype=c.params_dtype, init_method=out_init,
        )
        self.fc1 = tp.ColumnParallelLinear(
            c.hidden_size, c.ffn, axis=c.axis, gather_output=False,
            sequence_parallel=self._sp, comm_dtype=self._acd,
            params_dtype=c.params_dtype, init_method=init,
        )
        self.fc2 = tp.RowParallelLinear(
            c.ffn, c.hidden_size, axis=c.axis, input_is_parallel=True,
            sequence_parallel=self._sp, comm_dtype=self._acd,
            params_dtype=c.params_dtype, init_method=out_init,
        )

    # -- parameter helpers --------------------------------------------------

    def _ln_init(self) -> Params:
        c = self.cfg
        return {
            "scale": jnp.ones((c.hidden_size,), c.params_dtype),
            "bias": jnp.zeros((c.hidden_size,), c.params_dtype),
        }

    def _dense_init(self, key, n_in, n_out) -> Params:
        c = self.cfg
        return {
            "kernel": self._init(key, (n_in, n_out), c.params_dtype),
            "bias": jnp.zeros((n_out,), c.params_dtype),
        }

    def _layer_init(self, k) -> Params:
        ks = jax.random.split(k, 4)
        return {
            "ln1": self._ln_init(),
            "qkv": self.qkv.init(ks[0]),
            "proj": self.proj.init(ks[1]),
            "ln2": self._ln_init(),
            "fc1": self.fc1.init(ks[2]),
            "fc2": self.fc2.init(ks[3]),
        }

    def init_layer_stack(self, key) -> Params:
        """Stack per-layer trees along a leading num_layers dim (vmap over
        init is the cleanest way to build the scan-shaped stack)."""
        return jax.vmap(self._layer_init)(
            jax.random.split(key, self.cfg.num_layers))

    def layer_stack_specs(self) -> Params:
        ln = {"scale": P(), "bias": P()}
        return {
            "ln1": stack_specs(ln),
            "qkv": stack_specs(self.qkv.specs()),
            "proj": stack_specs(self.proj.specs()),
            "ln2": stack_specs(ln),
            "fc1": stack_specs(self.fc1.specs()),
            "fc2": stack_specs(self.fc2.specs()),
        }

    # -- compute helpers ----------------------------------------------------

    def _sp_param(self, x: jax.Array) -> jax.Array:
        """A REPLICATED parameter about to be consumed in a sequence-sharded
        region: each TP rank sees only its tokens, so AD alone would leave a
        PARTIAL per-rank gradient — and the harnesses' spec-aware reduction
        (allreduce_gradients_by_spec) never psums over the model axis for
        replicated params. The identity-forward/psum-backward ``copy_to``
        restores the plain-TP convention (full, identical grads on every TP
        rank) inside the differentiated function — the in-AD form of
        Megatron's sequence-parallel grad all-reduce."""
        if not self._sp:
            return x
        return tp.copy_to_tensor_model_parallel_region(x, self.cfg.axis)

    def _ln(self, p: Params, x: jax.Array,
            sequence_region: Optional[bool] = None) -> jax.Array:
        # Mixed-dtype fused LN: bf16 activations, fp32 γβ
        # (MixedFusedLayerNorm, fused_layer_norm.py:398-436). LNs sit in the
        # sequence-sharded region under sequence parallelism (that sharding
        # is the mode's memory win), so γβ ride _sp_param by default; head
        # LNs past the sequence gather pass sequence_region=False.
        scale, bias = p["scale"], p["bias"]
        if sequence_region is None or sequence_region:
            scale, bias = self._sp_param(scale), self._sp_param(bias)
        return fused_layer_norm_op(x, scale, bias)

    def _dense(self, p: Params, x: jax.Array) -> jax.Array:
        return x @ p["kernel"].astype(x.dtype) + p["bias"].astype(x.dtype)

    def _dropout(self, x, key, rank_unique: bool = False):
        c = self.cfg
        if key is None or c.hidden_dropout == 0.0:
            return x
        if self._sp:
            # sequence-sharded region: every hidden-dropout site in the
            # model zoo sits between a reduce-scatter and the next gather,
            # so each TP rank holds DIFFERENT tokens — fold the rank in
            # (tensor_parallel/random.py sequence_parallel_key) or the
            # shards would draw correlated masks
            key = tp.sequence_parallel_key(key, c.axis)
        elif rank_unique and c.axis is not None:
            key = tp.model_parallel_key(key, c.axis)
        return inverted_dropout(x, key, c.hidden_dropout)

    def _qkv_heads(self, p_qkv: Params, h: jax.Array,
                   positions: Optional[jax.Array] = None):
        """``(q, k, v)`` head tensors ``(b, n_local, s, d)`` from the fused
        QKV projection — the shared front half of :meth:`_attention`, also
        driven standalone by the serving prefill/decode paths (which need
        the raw k/v heads for the paged cache). ``positions`` overrides the
        rope positions with explicit PER-SEQUENCE ``(b, s)`` values (decode:
        each slot sits at its own context position); default is the
        training-forward :meth:`_token_positions`."""
        c = self.cfg
        b = h.shape[0]
        qkv = self.qkv.apply(p_qkv, h)  # (b, s, 3*H/tp)
        # under sequence parallelism h arrives (b, s/tp, H) and the
        # column layer's pre-GEMM all-gather restores the full
        # (context-local) sequence — read s from the GATHERED tensor
        s = qkv.shape[1]
        # (heads, 3, head_dim) layout: a TP shard holds whole heads — the
        # layout contract of ParallelAttention (standalone_gpt.py:560-640).
        n_local = qkv.shape[-1] // (3 * c.head_dim)
        qkv = qkv.reshape(b, s, n_local, 3, c.head_dim).transpose(0, 2, 3, 1, 4)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (b, nh, s, d)
        if getattr(c, "position_embedding", "learned") == "rope":
            theta = getattr(c, "rope_theta", 10000.0)
            if positions is None:
                pos = self._token_positions(s)
                q = apply_rope(q, pos, theta)
                k = apply_rope(k, pos, theta)
            else:
                q = apply_rope_at(q, positions, theta)
                k = apply_rope_at(k, positions, theta)
        return q, k, v

    def _attn_out(self, p: Params, attn: jax.Array) -> jax.Array:
        """Head-merge + output projection — the shared back half of
        :meth:`_attention` (also the serving decode epilogue)."""
        b, n_local, s, _ = attn.shape
        attn = attn.transpose(0, 2, 1, 3).reshape(
            b, s, n_local * self.cfg.head_dim)
        return self.proj.apply(p["proj"], attn)

    def _attention(self, p: Params, h: jax.Array, bias=None) -> jax.Array:
        # named scope = the per-op attribution key of pyprof.report (the
        # NVTX range the reference's nvmarker.py pushes around each module)
        with jax.named_scope("attention"):
            q, k, v = self._qkv_heads(p["qkv"], h)
            attn = self._attend(q, k, v, bias)
            return self._attn_out(p, attn)

    def _seq_shard_start(self, s_local: int):
        """Global position of this shard's first token for a tensor whose
        sequence dim is ``s_local`` long: the context-parallel offset
        (tokens arrive pre-sliced over ``context_axis``) plus the
        sequence-parallel offset (the embedding's reduce-scatter slices the
        context-local sequence a further tp ways). Returns a static 0 when
        neither axis shards the sequence."""
        c = self.cfg
        ctx = getattr(c, "context_axis", None)
        start = 0
        if ctx is not None:
            cp_local = s_local * (lax.axis_size(c.axis) if self._sp else 1)
            start = lax.axis_index(ctx) * cp_local
        if self._sp:
            start = start + lax.axis_index(c.axis) * s_local
        return start

    def _positions(self, pos_table: jax.Array, s_local: int) -> jax.Array:
        """Slice the learned position table for this shard's tokens —
        ``s_local`` is the LOCAL sequence length of the activation the
        positions are added to (context- and/or sequence-parallel-sharded);
        global positions start at :meth:`_seq_shard_start`. The table is a
        replicated param consumed per-shard, so under sequence parallelism
        it rides :meth:`_sp_param` for the grad bookkeeping (the
        context-axis slice needs no such wrap: the harness's pmean over the
        gradient-reduction axes recovers disjoint-row sums exactly)."""
        pos_table = self._sp_param(pos_table)
        ctx = getattr(self.cfg, "context_axis", None)
        if ctx is None and not self._sp:
            return pos_table[:s_local]
        return lax.dynamic_slice_in_dim(
            pos_table, self._seq_shard_start(s_local), s_local, axis=0)

    def _token_positions(self, s_local: int) -> jax.Array:
        """GLOBAL positions of this shard's tokens (for rotary embedding).
        Called on the GATHERED sequence inside attention, where only the
        context axis still shards the sequence — the sequence-parallel
        offset never applies here."""
        ctx = getattr(self.cfg, "context_axis", None)
        start = lax.axis_index(ctx) * s_local if ctx is not None else 0
        return start + jnp.arange(s_local, dtype=jnp.int32)

    def _attend(self, q, k, v, bias):
        """Core attention on (b, nh, s, d). With ``cfg.context_axis`` set the
        sequence dim is sharded over that mesh axis and attention runs as
        ring (ppermute KV block exchange) or Ulysses (all_to_all head
        exchange) sequence parallelism — shared by every model in the zoo
        (SURVEY.md §2.3 row SP: a new capability vs the reference)."""
        c = self.cfg
        ctx = getattr(c, "context_axis", None)
        win = getattr(c, "attention_window", None)
        seg = bias if isinstance(bias, SegmentMask) else None
        if ctx is None:
            if seg is not None:
                return flash_attention(
                    q, k, v, segment_ids=(seg.q_seg, seg.kv_seg),
                    pad_id=seg.pad_id, causal=self.causal,
                    impl=c.attention_impl, window=win)
            return flash_attention(q, k, v, bias=bias, causal=self.causal,
                                   impl=c.attention_impl, window=win)
        from apex_tpu.transformer.ring import ring_attention, ulysses_attention

        if bias is not None and seg is None:
            raise NotImplementedError(
                "a dense attention bias is not supported under sequence "
                "parallelism (it would have to be materialized (sq, SK) per "
                "shard); express masking as a SegmentMask — padding masks "
                "map directly (models/bert.py) — or run with "
                "context_axis=None")
        impls = {"ring": ring_attention, "ulysses": ulysses_attention}
        impl_name = getattr(c, "sequence_parallel_impl", "ring")
        if impl_name not in impls:
            raise ValueError(
                f"sequence_parallel_impl must be 'ring' or 'ulysses', "
                f"got {impl_name!r}")
        seg_kw = {}
        if seg is not None:
            seg_kw = dict(segment_ids=(seg.q_seg, seg.kv_seg),
                          pad_id=seg.pad_id)
        return impls[impl_name](
            q, k, v, axis=ctx, causal=self.causal, impl=c.attention_impl,
            window=win, **seg_kw)

    def _mlp(self, p: Params, h: jax.Array) -> jax.Array:
        with jax.named_scope("mlp"):
            return self.fc2.apply(
                p["fc2"], jax.nn.gelu(self.fc1.apply(p["fc1"], h)))

    def _layer(self, p: Params, h: jax.Array, key, bias=None) -> jax.Array:
        raise NotImplementedError

    # -- per-layer aux hooks (override point for layers that emit side
    # losses, e.g. MoE routers) ---------------------------------------------

    def _aux_init(self):
        """Zero-valued aux accumulator pytree, or None when layers emit no
        aux (the default)."""
        return None

    def _layer_aux(self, p: Params, h: jax.Array, key, bias):
        """``(h, aux)`` for one layer; default layers emit no aux."""
        return self._layer(p, h, key, bias), None

    def run_layers(
        self,
        layers: Params,
        h: jax.Array,
        attn_bias: Optional[jax.Array] = None,
        dropout_key: Optional[jax.Array] = None,
        return_aux: bool = False,
        chunk_meta=None,
    ):
        """Scan the (stacked) layer params over the hidden state. ``layers``
        may be any contiguous slice of the stack — a pipeline stage's chunk.
        Activation checkpointing is ``jax.checkpoint`` on the scanned body
        (reference: tensor_parallel/random.py:224-294 CheckpointFunction).

        ``chunk_meta`` (optimizers.distributed.ChunkedMeta, per-LAYER local
        shapes) switches to the ZeRO-3 fully-sharded drive: ``layers`` is
        then a ``(L, k)`` per-row chunk stack and each layer's full weight
        tree is all-gathered JUST IN TIME inside the body — so peak param
        residency is one layer plus chunks, not the whole stack. The body
        is always rematerialized in this mode (even with ``cfg.remat``
        off): backward then RE-GATHERS each layer instead of saving the
        gathered weights as residuals, and the gather's AD transpose
        reduce-scatters that layer's grads on the spot. On the unrolled
        path the per-layer gathers are static, independent collectives;
        with ``cfg.zero3_prefetch > 0`` they are DOUBLE-BUFFERED
        explicitly (:func:`_prefetched_zero3_drive`: layer i+prefetch's
        gather issues before layer i's compute, forward and backward)
        instead of leaving the overlap to XLA's latency-hiding scheduler
        — the structural form the ``unprefetched_gather_hazards``
        tripwire checks for (peak residency: prefetch+1 layers + chunks).

        When the model's layers emit aux losses (``_aux_init`` not None),
        they accumulate in the scan carry and the caller MUST pass
        ``return_aux=True`` — silently discarding router losses would turn
        the MoE balancing knobs into no-ops."""
        n = jax.tree.leaves(layers)[0].shape[0]
        keys = None if dropout_key is None else jax.random.split(dropout_key, n)
        aux0 = self._aux_init()
        if aux0 is not None and not return_aux:
            raise ValueError(
                "this model's layers emit aux losses (MoE router); call "
                "run_layers(..., return_aux=True) and fold them into the "
                "loss — dropping them silently disables load balancing. "
                "Under the pipeline schedules, pass run_layers with "
                "return_aux=True plus aux_to_loss to pipelined_loss_fn."
            )
        if chunk_meta is not None:
            from apex_tpu.optimizers.distributed import gather_chunked_tree

            prefetch = int(getattr(self.cfg, "zero3_prefetch", 0) or 0)
            if prefetch > 0:
                if not getattr(self.cfg, "unroll_layers", False):
                    raise ValueError(ZERO3_PREFETCH_NEEDS_UNROLL)
                if aux0 is not None:
                    raise ValueError(
                        "zero3_prefetch does not support aux-emitting "
                        "layers (MoE routers) — ZeRO rejects data-sharded "
                        "experts anyway")
                if keys is not None or attn_bias is not None:
                    raise NotImplementedError(
                        "zero3_prefetch drives the dense dropout-off path "
                        "only: the custom-VJP drive would need dropout-key"
                        "/attention-bias cotangent plumbing no ZeRO-3 "
                        "harness exercises")
                drive = _prefetched_zero3_drive(
                    lambda p, hh: self._layer(p, hh, None, None),
                    lambda c: gather_chunked_tree(c, chunk_meta),
                    n, prefetch)
                h = drive(layers, h)
                return (h, None) if return_aux else h

        def body(carry, xs):
            h, acc = carry
            p, k = xs
            if chunk_meta is not None:
                p = gather_chunked_tree(p, chunk_meta)
            h, aux = self._layer_aux(p, h, k, attn_bias)
            if acc is not None:
                acc = jax.tree.map(
                    jnp.add, acc,
                    jax.tree.map(lambda v: v.astype(jnp.float32), aux))
            return (h, acc), None

        if self.cfg.remat or chunk_meta is not None:
            body = jax.checkpoint(
                body, prevent_cse=False,
                policy=_remat_policy(getattr(self.cfg, "remat_policy", None)),
            )
        if getattr(self.cfg, "unroll_layers", False):
            # Unrolled drive of the SAME stacked params: static per-layer
            # slices in a Python loop. The scan's backward writes each
            # layer's grads through dynamic-update-slice fusions (~28 ms
            # per 345M grad step on-chip, 11%) which the static-slice
            # adjoints avoid entirely — measured 230 -> 188 ms (PERF_NOTES
            # r5). Same math, same order, same tree; compile time grows
            # O(depth).
            carry = (h, aux0)
            for i in range(n):
                xs = (jax.tree.map(lambda v: v[i], layers),
                      None if keys is None else keys[i])
                carry, _ = body(carry, xs)
            h, aux = carry
            return (h, aux) if return_aux else h
        (h, aux), _ = lax.scan(body, (h, aux0), (layers, keys))
        return (h, aux) if return_aux else h
