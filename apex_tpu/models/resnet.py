"""ResNet (v1.5) — the framework's convnet example/benchmark vehicle.

Reference: ``examples/imagenet/main_amp.py`` builds its models from
``torchvision.models`` (main_amp.py:160-166) and trains them with amp O0-O3 +
apex DDP + optional ``convert_syncbn_model``; the L1 convergence suite sweeps
ResNet-50 across the full opt-level cross-product (tests/L1/common/run_test.sh:
30-80). The reference repo therefore needs a vendored ResNet only implicitly —
this module is the TPU-native equivalent of that torchvision dependency, so the
imagenet recipe (BASELINE.md configs 1-2) is self-contained.

TPU-first choices:

- **NHWC layout** (channel-last): the native TPU convolution layout — the
  reference gets this only through its experimental ``--channels-last`` flag
  (main_amp.py:31,168-177) and the NHWC groupbn extension.
- Normalization is **pluggable** via ``norm``: plain local BN by default, or
  :class:`apex_tpu.parallel.SyncBatchNorm` over a mesh axis by passing
  ``axis_name`` (the role of ``convert_syncbn_model``, main_amp.py:180-182).
  conv→bn→relu chains use ``fuse_relu`` so the whole pattern is one fused XLA
  region (the groupbn BN+ReLU fusion, apex/contrib/groupbn/batch_norm.py).
- Compute dtype is a parameter; amp's ``cast_params`` keeps the ``bn*``
  parameters fp32 under O2's ``keep_batchnorm_fp32`` because the layer names
  carry the ``bn`` marker (precision.cast_params).
- v1.5 stride placement: stride-2 lives on the 3x3 conv of the bottleneck
  (torchvision semantics), the variant the reference's imagenet recipe trains.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm

ModuleDef = Callable[..., nn.Module]

# Standalone-block default: local BN, NHWC. ResNet overrides this with its
# own (possibly axis-synced) factory.
_default_norm = partial(SyncBatchNorm, channel_last=True)


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (ResNet-18/34)."""

    filters: int
    strides: int = 1
    norm: ModuleDef = _default_norm
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype, name=None)
        residual = x
        y = conv(self.filters, (3, 3), strides=self.strides, padding=1, name="conv1")(x)
        y = self.norm(fuse_relu=True, name="bn1")(y, use_running_average)
        y = conv(self.filters, (3, 3), padding=1, name="conv2")(y)
        y = self.norm(name="bn2")(y, use_running_average)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1), strides=self.strides, name="conv_ds")(x)
            residual = self.norm(name="bn_ds")(residual, use_running_average)
        return jax.nn.relu(y + residual)


class Bottleneck(nn.Module):
    """1x1 → 3x3 (stride here: v1.5) → 1x1 residual block (ResNet-50+)."""

    filters: int
    strides: int = 1
    norm: ModuleDef = _default_norm
    dtype: Any = jnp.float32
    expansion: int = 4

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        return self._forward(x, self.norm, use_running_average)

    def _forward(self, x, norm, use_running_average):
        """Block body, parameterized on the norm factory so subclasses
        (contrib.bottleneck.FastBottleneck) can pin a different norm
        without duplicating the structure."""
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        out = self.filters * self.expansion
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = norm(fuse_relu=True, name="bn1")(y, use_running_average)
        y = conv(self.filters, (3, 3), strides=self.strides, padding=1, name="conv2")(y)
        y = norm(fuse_relu=True, name="bn2")(y, use_running_average)
        y = conv(out, (1, 1), name="conv3")(y)
        y = norm(name="bn3")(y, use_running_average)
        if residual.shape != y.shape:
            residual = conv(out, (1, 1), strides=self.strides, name="conv_ds")(x)
            residual = norm(name="bn_ds")(residual, use_running_average)
        return jax.nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet over NHWC inputs; ``__call__(images) -> logits``.

    ``axis_name`` turns every BN into a SyncBatchNorm over that mesh axis
    (with optional ``bn_group_size`` sub-grouping, the
    ``create_syncbn_process_group`` knob). ``norm_cls`` swaps the norm
    implementation wholesale (it must accept SyncBatchNorm's constructor
    surface: ``momentum``/``axis_name``/``group_size``/``channel_last`` and
    a ``fuse_relu`` flag).
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    width: int = 64
    axis_name: Optional[str] = None
    bn_group_size: Optional[int] = None
    norm_cls: ModuleDef = SyncBatchNorm
    dtype: Any = jnp.float32
    stem_pool: bool = True  # False for cifar-sized inputs in tests

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        norm = partial(
            self.norm_cls,
            momentum=0.1,
            axis_name=self.axis_name,
            group_size=self.bn_group_size,
            channel_last=True,
        )
        x = x.astype(self.dtype)
        if self.stem_pool:
            x = nn.Conv(self.width, (7, 7), strides=2, padding=3, use_bias=False,
                        dtype=self.dtype, name="conv1")(x)
        else:
            x = nn.Conv(self.width, (3, 3), padding=1, use_bias=False,
                        dtype=self.dtype, name="conv1")(x)
        x = norm(fuse_relu=True, name="bn1")(x, use_running_average)
        if self.stem_pool:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if (i > 0 and j == 0) else 1
                x = self.block_cls(
                    filters=self.width * 2**i,
                    strides=strides,
                    norm=norm,
                    dtype=self.dtype,
                    name=f"layer{i + 1}_{j}",
                )(x, use_running_average)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        # classifier in fp32 (amp keeps the last matmul's logits fp32-safe:
        # functional_overrides FP32 list treats losses/softmax as fp32).
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x.astype(jnp.float32))
        return x


def _resnet(stage_sizes, block_cls, **kw) -> ResNet:
    return ResNet(stage_sizes=stage_sizes, block_cls=block_cls, **kw)


ResNet18 = partial(_resnet, (2, 2, 2, 2), BasicBlock)
ResNet34 = partial(_resnet, (3, 4, 6, 3), BasicBlock)
ResNet50 = partial(_resnet, (3, 4, 6, 3), Bottleneck)
ResNet101 = partial(_resnet, (3, 4, 23, 3), Bottleneck)
ResNet152 = partial(_resnet, (3, 8, 36, 3), Bottleneck)


def _frozen_resnet(stage_sizes, **kw) -> ResNet:
    """ResNet with every BN frozen to per-channel scale/bias — the
    detection-backbone configuration of the reference's fast_bottleneck
    extension (apex/contrib/bottleneck/bottleneck.py): FastBottleneck
    blocks plus a frozen stem norm."""
    from apex_tpu.contrib.bottleneck import FastBottleneck, FrozenBatchNorm

    return ResNet(stage_sizes=stage_sizes, block_cls=FastBottleneck,
                  norm_cls=FrozenBatchNorm, **kw)


ResNet50Frozen = partial(_frozen_resnet, (3, 4, 6, 3))
ResNet101Frozen = partial(_frozen_resnet, (3, 4, 23, 3))
