"""Reference model zoo (reference: apex/transformer/testing/standalone_gpt.py,
standalone_bert.py, examples/imagenet, apex/mlp, apex/fused_dense).

These are the framework's example applications *and* its benchmark/test
vehicles, the role standalone_gpt.py plays for the reference test suite.
"""

from apex_tpu.models.bert import BertConfig, BertModel  # noqa: F401
from apex_tpu.models.gpt import GPTConfig, GPTModel  # noqa: F401
from apex_tpu.models.mlp import MLP  # noqa: F401
from apex_tpu.models.fused_dense import FusedDense, FusedDenseGeluDense  # noqa: F401
from apex_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
