"""Fused MLP (reference: apex/mlp/mlp.py:8-79 + csrc/mlp_cuda.cu).

The reference runs an entire multi-layer perceptron (chained GEMMs + fused
bias/activation epilogues) in one extension call to amortize launch overhead
and keep intermediates out of global memory. Under XLA the same chain,
expressed as one jitted function, compiles to exactly that — GEMMs with fused
bias/activation epilogues on the MXU — so the TPU-native MLP is the
composition itself; no custom kernel can beat what the compiler already does
here (SURVEY.md §7 step 3: "benchmark first; keep the API, let impl be lax
if XLA wins").
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Sequence

import jax
import jax.numpy as jnp


_ACTIVATIONS = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
}


@dataclasses.dataclass
class MLP:
    """Drop-in MLP module (apex/mlp/mlp.py:44-79).

    ``mlp_sizes`` lists layer widths including input, e.g. (480, 1024, 960).
    ``activation`` ∈ {'none', 'relu', 'sigmoid'} applies between layers and
    after the last (matching the reference kernel's epilogue placement).
    """

    mlp_sizes: Sequence[int]
    bias: bool = True
    activation: str = "relu"
    params_dtype: Any = jnp.float32

    def __post_init__(self):
        if len(self.mlp_sizes) < 2:
            raise ValueError("need at least input and one layer size")
        if self.activation not in _ACTIVATIONS:
            raise ValueError(f"unknown activation {self.activation!r}")

    def init(self, key: jax.Array) -> List[Dict[str, jax.Array]]:
        layers = []
        for i, (n_in, n_out) in enumerate(zip(self.mlp_sizes[:-1], self.mlp_sizes[1:])):
            k = jax.random.fold_in(key, i)
            # Reference resets weights uniform(-1/sqrt(fan_in), +) like
            # nn.Linear (mlp.py:66-73).
            bound = 1.0 / (n_in ** 0.5)
            p = {
                "kernel": jax.random.uniform(
                    k, (n_in, n_out), self.params_dtype, -bound, bound
                )
            }
            if self.bias:
                p["bias"] = jax.random.uniform(
                    jax.random.fold_in(k, 1), (n_out,), self.params_dtype, -bound, bound
                )
            layers.append(p)
        return layers

    def apply(self, params: List[Dict[str, jax.Array]], x: jax.Array) -> jax.Array:
        act = _ACTIVATIONS[self.activation]
        for p in params:
            x = x @ p["kernel"].astype(x.dtype)
            if "bias" in p:
                x = x + p["bias"].astype(x.dtype)
            x = act(x)
        return x

    def __call__(self, params, x):
        return self.apply(params, x)
