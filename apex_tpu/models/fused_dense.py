"""Fused dense layers (reference: apex/fused_dense/fused_dense.py:6-85 +
csrc/fused_dense_cuda.cu cublasLt epilogues).

``FusedDense`` = GEMM + bias; ``FusedDenseGeluDense`` = GEMM + bias + GeLU +
GEMM + bias, the cublasLt epilogue-fusion chain. On TPU, XLA fuses these
epilogues into the MXU matmuls when they appear in one jitted function, so
the module is the API shape, the compiler is the kernel.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp

Params = Dict[str, jax.Array]


def _linear_init(key, n_in, n_out, dtype):
    bound = 1.0 / (n_in ** 0.5)
    return {
        "kernel": jax.random.uniform(key, (n_in, n_out), dtype, -bound, bound),
        "bias": jax.random.uniform(
            jax.random.fold_in(key, 1), (n_out,), dtype, -bound, bound
        ),
    }


@dataclasses.dataclass
class FusedDense:
    """GEMM + bias (FusedDense, fused_dense.py:6-35)."""

    in_features: int
    out_features: int
    params_dtype: Any = jnp.float32

    def init(self, key: jax.Array) -> Params:
        return _linear_init(key, self.in_features, self.out_features, self.params_dtype)

    def apply(self, params: Params, x: jax.Array) -> jax.Array:
        return x @ params["kernel"].astype(x.dtype) + params["bias"].astype(x.dtype)

    __call__ = apply


@dataclasses.dataclass
class FusedDenseGeluDense:
    """GEMM+bias+GeLU+GEMM+bias (FusedDenseGeluDense, fused_dense.py:38-85)."""

    in_features: int
    intermediate_features: int
    out_features: int
    params_dtype: Any = jnp.float32

    def init(self, key: jax.Array) -> Params:
        k1, k2 = jax.random.split(key)
        return {
            "dense1": _linear_init(k1, self.in_features, self.intermediate_features,
                                   self.params_dtype),
            "dense2": _linear_init(k2, self.intermediate_features, self.out_features,
                                   self.params_dtype),
        }

    def apply(self, params: Params, x: jax.Array) -> jax.Array:
        h = x @ params["dense1"]["kernel"].astype(x.dtype)
        h = jax.nn.gelu(h + params["dense1"]["bias"].astype(x.dtype))
        return h @ params["dense2"]["kernel"].astype(x.dtype) + params["dense2"][
            "bias"
        ].astype(x.dtype)

    __call__ = apply
