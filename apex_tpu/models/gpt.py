"""Megatron-style GPT (reference: apex/transformer/testing/standalone_gpt.py:236-1517).

The reference vendors a full Megatron GPT (ParallelMLP, ParallelAttention,
ParallelTransformer, Embedding, GPTModel) as the test/benchmark vehicle for
its transformer framework. This is the TPU-native counterpart, built from
apex_tpu.transformer.tensor_parallel layers:

- token embedding: ``VocabParallelEmbedding`` (+ learned positions);
- per layer: LN → fused-QKV ``ColumnParallelLinear`` (no gather; output laid
  out ``(heads, 3, head_dim)`` so a TP shard holds whole heads, the layout
  contract of ParallelAttention, standalone_gpt.py:560-640) → flash attention
  on local heads → ``RowParallelLinear`` projection → residual → LN →
  column/row MLP with GeLU → residual;
- final LN → tied vocab-parallel LM head → ``vocab_parallel_cross_entropy``.

TPU-first structural choices (vs the reference's per-layer nn.ModuleList):

- layer parameters are **stacked** on a leading ``(num_layers, ...)`` dim and
  the stack is driven by ``lax.scan`` — one traced layer body regardless of
  depth (compile time O(1) in layers), and the natural shape for pipeline
  stages to slice;
- activation checkpointing is ``jax.checkpoint`` on the scanned body
  (reference: tensor_parallel/random.py:224-294 CheckpointFunction);
- dropout randomness comes from an explicit key, split per layer and folded
  per TP rank where state must differ (random.py:174-191 semantics).

Serial (``axis=None``) and shard_map-parallel execution use the same params
and the same code path, like the rest of the framework.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.models._transformer import TransformerBase
from apex_tpu.parallel.mesh import AXIS_MODEL
from apex_tpu.transformer import tensor_parallel as tp

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    """Model hyperparameters (reference: testing/arguments.py essentials)."""

    vocab_size: int = 50304
    hidden_size: int = 1024
    num_layers: int = 24
    num_attention_heads: int = 16
    max_seq_len: int = 1024
    ffn_hidden_size: Optional[int] = None  # default 4*hidden
    axis: Optional[str] = AXIS_MODEL  # tensor-parallel mesh axis (None=serial)
    # Megatron-style sequence parallelism ON THE TP AXIS (distinct from
    # context_axis/sequence_parallel_impl below, which shard attention
    # itself): each layer's two forward TP all-reduces decompose into
    # psum_scatter + all_gather conjugates and the LN/dropout/residual
    # regions run sequence-sharded (b, s/tp, h) — 1/tp the activation
    # bytes there, and two schedulable collectives instead of one
    # synchronous all-reduce (VERDICT r5: all 9 TP all-reduces compiled
    # synchronous). Ignored when axis is None; requires max_seq_len
    # divisible by tp. No reference analog (apex predates Megatron SP).
    sequence_parallel: bool = False
    # Quantized wire dtype ("int8" | "e5m2") for the sequence-parallel
    # activation conjugates (requires sequence_parallel=True): the
    # scatter/gather payloads encode to 1 B/elem with per-shard fp32
    # scales riding a tiny side-channel (parallel/quantize.py), summed in
    # fp32 after decode. Activations carry no error-feedback residual —
    # fresh values every step bound the error by the per-shard scale.
    # None = exact wire (the default; traces bit-identical to pre-knob).
    activation_comm_dtype: Optional[str] = None
    params_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    hidden_dropout: float = 0.1
    init_method_std: float = 0.02
    remat: bool = True  # activation checkpointing per layer
    # selective checkpoint policy: None/"full" | "save_attn" | "dots"
    # (models/_transformer._remat_policy)
    remat_policy: Optional[str] = None
    attention_impl: str = "auto"  # flash_attention impl switch
    # Sliding-window (local) attention: each token attends only its
    # `attention_window` most recent positions (flash_attention's `window`
    # semantics). O(s·w) attention cost — the standard long-context pairing
    # with the streamed kernels; composes with context parallelism (the
    # window is defined in global positions and rides the ring offsets).
    # None = full attention. Beyond-reference capability.
    attention_window: Optional[int] = None
    # Position encoding: "learned" (reference parity — a trained
    # (max_seq_len, hidden) table) | "rope" (rotary on q/k, NO position
    # params at all: at 1M tokens the learned table is ~3.75 GB of
    # params + Adam state; relative-distance property makes it exact
    # under context parallelism with shard-offset positions) | "none".
    position_embedding: str = "learned"
    rope_theta: float = 10000.0
    # Drive the (still stacked) layer params with an unrolled Python loop
    # of static per-layer slices instead of lax.scan. Measured on-chip at
    # 345M: the scan's backward accumulates layer grads through
    # dynamic-update-slice fusions (~28 ms/step, 11% of the grad step) and
    # pins the remat recompute; the unrolled body drops the grad step
    # 230 -> 188 ms (PERF_NOTES r5). Cost: compile time O(depth) instead
    # of O(1) — fine at flagship depth, keep False for very deep or
    # pipelined configs (pipeline stages already slice the stack).
    unroll_layers: bool = False
    # ZeRO-3 gather prefetch depth (unrolled path only): double-buffer the
    # per-layer just-in-time chunk all-gathers — issue layer i+N's gather
    # before layer i's compute, forward AND backward re-gathers
    # (models/_transformer._prefetched_zero3_drive), so the gathers stand
    # structurally ahead of the compute that hides them instead of pinned
    # inside the rematerialized body. 0 = the serialized in-body gather;
    # N=1 is classic double buffering. Peak param residency grows to
    # N+1 layers + chunks. Tripwire: lint.trace.unprefetched_gather_hazards.
    zero3_prefetch: int = 0
    # chunked fused LM-head CE (ops/lm_head_loss): avoids materializing the
    # (tokens, vocab) logits when computing the loss. Serial (axis=None) only;
    # under TP the vocab is already sharded V/tp ways.
    lm_head_chunks: Optional[int] = None
    # sequence/context parallelism (long-context; NEW vs the reference,
    # SURVEY.md §2.3 row SP): shard the sequence dim over this mesh axis and
    # attend with ring attention (ppermute block exchange) or Ulysses
    # all-to-all. Run under shard_map with tokens sharded on dim 1.
    context_axis: Optional[str] = None
    sequence_parallel_impl: str = "ring"  # 'ring' | 'ulysses'
    # mixture-of-experts FFN (NEW vs the reference, SURVEY.md §2.3 row EP):
    # when moe_num_experts is set, every layer's dense FFN becomes a top-k
    # routed MoEMLP (transformer/moe.py). moe_expert_axis shards experts
    # over that mesh axis with all_to_all dispatch — run under shard_map
    # with the batch dim sharded over the same axis (the data axis).
    moe_num_experts: Optional[int] = None
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_expert_axis: Optional[str] = None
    moe_aux_loss_weight: float = 0.01
    moe_z_loss_weight: float = 1e-3
    # Quantized wire dtype ("int8" | "e5m2") for the expert-parallel
    # dispatch/combine all_to_all payloads (requires moe_expert_axis when
    # set; ignored on a serial build — the serial-twin convention of
    # activation_comm_dtype): token buckets encode to 1 B/elem with fp32
    # per-destination-block scales riding a tiny side-channel, forward AND
    # backward (parallel/quantize.quantized_all_to_all). Activations carry
    # no error-feedback residual. None = exact wire.
    moe_dispatch_dtype: Optional[str] = None

    @property
    def ffn(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


class GPTModel(TransformerBase):
    """Functional GPT with TP-sharded params (GPTModel, standalone_gpt.py:1361+).

    ``init(key)`` → full param tree; ``specs()`` → PartitionSpec tree;
    ``apply(params, tokens, targets=..., dropout_key=...)`` → per-token loss
    (or logits when ``targets`` is None). ``embed`` / ``run_layers`` /
    ``head`` expose the stage boundaries pipeline schedules need (the
    functional replacement for the reference's pre_process/post_process
    flags and set_input_tensor, pipeline_parallel/schedules/common.py:24-112).
    Shared transformer plumbing lives in TransformerBase (models/_transformer).
    """

    causal = True

    def __init__(self, config):
        super().__init__(config)
        c = config
        if c.position_embedding not in ("learned", "rope", "none"):
            raise ValueError(
                f"position_embedding must be learned|rope|none, got "
                f"{c.position_embedding!r}")
        if c.position_embedding == "rope" and c.head_dim % 2:
            raise ValueError(
                f"rope needs an even head_dim, got {c.head_dim}")
        if c.sequence_parallel and c.moe_num_experts is not None:
            raise ValueError(
                "sequence_parallel does not compose with MoE FFNs yet: the "
                "router must see gathered tokens (the dense fc1/fc2 gather/"
                "reduce-scatter pair has no MoE counterpart here)")
        if c.moe_num_experts is not None:
            from apex_tpu.transformer.moe import MoEMLP

            self.moe = MoEMLP(
                c.hidden_size, c.ffn, num_experts=c.moe_num_experts,
                top_k=c.moe_top_k, capacity_factor=c.moe_capacity_factor,
                expert_axis=c.moe_expert_axis,
                tp_axis=c.axis,  # expert FFNs ride the model axis (EP x TP)
                params_dtype=c.params_dtype,
                init_method=tp.scaled_normal(c.init_method_std),
                # serial-twin convention (activation_comm_dtype): a serial
                # build of an expert-parallel config must run — there is
                # no dispatch wire to quantize without the expert axis
                dispatch_dtype=(c.moe_dispatch_dtype
                                if c.moe_expert_axis is not None else None),
            )

    # -- parameters ---------------------------------------------------------

    def _layer_init(self, k: jax.Array) -> Params:
        if self.cfg.moe_num_experts is None:
            return super()._layer_init(k)
        # build only what the MoE block uses — initializing the dense
        # fc1/fc2 just to discard them would materialize the full FFN
        # weights once per layer under the vmapped stack init
        ks = jax.random.split(k, 3)
        return {
            "ln1": self._ln_init(),
            "qkv": self.qkv.init(ks[0]),
            "proj": self.proj.init(ks[1]),
            "ln2": self._ln_init(),
            "moe": self.moe.init(ks[2]),
        }

    def layer_stack_specs(self) -> Params:
        if self.cfg.moe_num_experts is None:
            return super().layer_stack_specs()
        from apex_tpu.models._transformer import stack_specs

        ln = {"scale": P(), "bias": P()}
        return {
            "ln1": stack_specs(ln),
            "qkv": stack_specs(self.qkv.specs()),
            "proj": stack_specs(self.proj.specs()),
            "ln2": stack_specs(ln),
            "moe": stack_specs(self.moe.specs()),
        }

    def init(self, key: jax.Array) -> Params:
        c = self.cfg
        keys = jax.random.split(key, 4)
        tree = {
            "embedding": self.embedding.init(keys[0]),
            "layers": self.init_layer_stack(keys[2]),
            "ln_f": self._ln_init(),
        }
        if c.position_embedding == "learned":
            tree["position"] = tp.scaled_normal(c.init_method_std)(
                keys[1], (c.max_seq_len, c.hidden_size), c.params_dtype)
        return tree

    def specs(self) -> Params:
        tree = {
            "embedding": self.embedding.specs(),
            "layers": self.layer_stack_specs(),
            "ln_f": {"scale": P(), "bias": P()},
        }
        if self.cfg.position_embedding == "learned":
            tree["position"] = P()
        return tree

    # -- stages -------------------------------------------------------------

    def embed(self, params: Params, tokens: jax.Array) -> jax.Array:
        c = self.cfg
        with jax.named_scope("embed"):
            h = self.embedding.apply(params["embedding"], tokens)
            if c.position_embedding == "learned":
                # positions add AFTER the embedding's closing collective
                # (h.shape[1] is the sequence-parallel shard under SP):
                # adding them to the pre-reduce partial sums would count
                # them once per TP rank through the psum/psum_scatter
                h = h + self._positions(params["position"], h.shape[1])
            # "rope": positions enter at the q/k rotation in _attention;
            # "none": no positional signal at the embedding
            return h.astype(c.compute_dtype)

    def _layer(self, p: Params, h: jax.Array, key, bias=None) -> jax.Array:
        """Pre-LN block: residual + sublayer(LN(h))."""
        return self._layer_aux(p, h, key, bias)[0]

    def _aux_init(self):
        if self.cfg.moe_num_experts is None:
            return None
        return {"load_balancing_loss": jnp.zeros(()),
                "router_z_loss": jnp.zeros(()),
                # summed over layers by run_layers; divide by num_layers
                # for the mean per-layer drop rate (pure metric)
                "dropped_fraction": jnp.zeros(())}

    def _layer_aux(self, p: Params, h: jax.Array, key, bias):
        """One pre-LN block body for both FFN variants: dense MLP (aux is
        None) or routed experts (aux = router losses)."""
        c = self.cfg
        k1, k2 = (None, None) if key is None else tuple(jax.random.split(key))
        # Post-residual dropout is replicated across TP ranks (same key);
        # the reference draws it from the default (data-parallel) RNG state.
        h = h + self._dropout(self._attention(p, self._ln(p["ln1"], h), bias), k1)
        x = self._ln(p["ln2"], h)
        if c.moe_num_experts is None:
            out, aux = self._mlp(p, x), None
        elif c.moe_expert_axis is not None:
            out, aux = self.moe.apply_expert_parallel(p["moe"], x)
        else:
            out, aux = self.moe.apply(p["moe"], x)
        return h + self._dropout(out, k2), aux

    def head(
        self, params: Params, h: jax.Array,
        targets: Optional[jax.Array] = None,
    ):
        """Final LN + tied LM head (+ per-token loss when targets given)
        (post_language_model_processing, standalone_gpt.py:1361+)."""
        c = self.cfg
        with jax.named_scope("head"):
            h = self._ln(params["ln_f"], h)
            if c.axis is None and c.lm_head_chunks and targets is not None:
                from apex_tpu.ops.lm_head_loss import lm_head_cross_entropy

                return lm_head_cross_entropy(
                    h, params["embedding"]["embedding"], targets,
                    c.lm_head_chunks)
            wte = params["embedding"]["embedding"].astype(h.dtype)  # (V/tp, H)
            if c.axis is not None:
                if c.sequence_parallel:
                    # close the sequence-sharded region: all-gather forward;
                    # the backward reduce-scatter sums the per-vocab-shard
                    # partial cotangents AND re-shards the sequence — the
                    # copy_to psum and the scatter in one conjugate
                    h = tp.gather_from_sequence_parallel_region(
                        h, c.axis, True, self._acd)
                else:
                    h = tp.copy_to_tensor_model_parallel_region(h, c.axis)
            logits = jnp.einsum("bsh,vh->bsv", h, wte)  # vocab-sharded logits
            if targets is None:
                return logits
            return tp.vocab_parallel_cross_entropy(logits, targets, axis=c.axis)

    def aux_to_loss(self, aux) -> jax.Array:
        """Canonical (linear) fold of accumulated router aux losses into a
        scalar loss term — the single definition shared by serial ``apply``,
        the pipelined ``aux_to_loss`` hook, and the multi-chip gate."""
        c = self.cfg
        return (c.moe_aux_loss_weight * aux["load_balancing_loss"]
                + c.moe_z_loss_weight * aux["router_z_loss"]) / c.num_layers

    def apply(
        self,
        params: Params,
        tokens: jax.Array,
        targets: Optional[jax.Array] = None,
        dropout_key: Optional[jax.Array] = None,
        layer_chunk_meta=None,
    ):
        """``layer_chunk_meta`` drives the ZeRO-3 fully-sharded path:
        ``params["layers"]`` is then a per-row chunk stack gathered
        just-in-time per layer (run_layers ``chunk_meta``); the non-layer
        params must arrive already gathered (the step wrapper's job —
        transformer/amp.build_zero_train_step)."""
        h = self.embed(params, tokens)
        h, aux = self.run_layers(params["layers"], h, dropout_key=dropout_key,
                                 return_aux=True,
                                 chunk_meta=layer_chunk_meta)
        out = self.head(params, h, targets)
        if aux is not None and targets is not None:
            # fold per-layer-averaged router losses into the per-token loss
            # (a scalar added uniformly keeps the mean-loss contract)
            out = out + self.aux_to_loss(aux).astype(out.dtype)
        return out

    # -- serving drives (apex_tpu/serve/engine.py) --------------------------
    # Inference-only siblings of embed/run_layers/head: same parameter tree,
    # same per-token math (so greedy decode bit-matches the training
    # forward's argmax — the serve equivalence gate), but threaded through
    # the paged KV cache instead of recomputing the whole context per token.

    def check_servable(self) -> None:
        """Serving composes with TP, attention_window, and MoE FFNs
        (serial experts or expert-parallel decode: per-tick top-k routing
        is data, not shapes, so the decode program stays shape-stable —
        :meth:`_serve_ffn`); the modes that reshape the sequence (CP
        rings, Megatron SP) have no decode-cache story yet — fail loudly
        at engine build. An expert-parallel build (``moe_expert_axis``)
        additionally needs the mesh at the engine (engine-side check)."""
        c = self.cfg
        if getattr(c, "context_axis", None) is not None:
            raise ValueError(
                "serving does not support context parallelism: the paged "
                "cache is per-slot, not ring-sharded — run decode with "
                "context_axis=None")
        if self._sp:
            raise ValueError(
                "serving does not support sequence_parallel=True: decode "
                "works on single-token sequences that cannot shard s/tp "
                "ways — build the serve model with sequence_parallel=False")

    def embed_at(self, params: Params, tokens: jax.Array,
                 positions: jax.Array) -> jax.Array:
        """:meth:`embed` at EXPLICIT per-slot positions ``(b, s)`` — at a
        decode tick every slot's new token sits at its own context
        position, so the training method's ``[0, s)`` slice cannot serve.
        Same math (embedding collective + position-row add) at equal
        positions."""
        c = self.cfg
        with jax.named_scope("embed"):
            h = self.embedding.apply(params["embedding"], tokens)
            if c.position_embedding == "learned":
                h = h + jnp.take(params["position"], positions, axis=0)
            return h.astype(c.compute_dtype)

    def _serve_ffn(self, p: Params, x: jax.Array) -> jax.Array:
        """The FFN half of a serving layer: the dense MLP, or the routed
        MoE block at inference (aux losses dropped — nothing trains).
        Expert-parallel builds dispatch through the token-replicated
        conjugate (``MoEMLP.apply_expert_sharded``: identical routing on
        every rank, local-expert compute, one psum combine — the same
        function as serial ``apply``, so greedy streams match the serial
        engine's bit for bit)."""
        c = self.cfg
        if c.moe_num_experts is None:
            return self._mlp(p, x)
        if c.moe_expert_axis is not None:
            return self.moe.apply_expert_sharded(p["moe"], x)
        return self.moe.apply(p["moe"], x)[0]

    def serve_layers_prefill(self, layers: Params, h: jax.Array):
        """Run the layer stack over a PROMPT, collecting every layer's k/v
        head tensors for the cache fill. Returns ``(h, k, v)`` with k/v
        shaped ``(num_layers, b, n_local_heads, s, head_dim)``. Attention
        is the training `_attend` (causal + ``attention_window``), so
        prefill hidden states match the training forward exactly."""

        def body(h, p):
            x = self._ln(p["ln1"], h)
            q, k, v = self._qkv_heads(p["qkv"], x)
            h = h + self._attn_out(p, self._attend(q, k, v, None))
            h = h + self._serve_ffn(p, self._ln(p["ln2"], h))
            return h, (k, v)

        h, (ks, vs) = lax.scan(body, h, layers)
        return h, ks, vs

    def serve_layers_decode(self, layers: Params, h: jax.Array,
                            k_pages: jax.Array, v_pages: jax.Array,
                            block_tables: jax.Array, write_flat: jax.Array,
                            attend_lengths: jax.Array,
                            positions: jax.Array):
        """One decode tick through the layer stack: for each layer, write
        the new token's k/v heads into the paged cache (``write_flat``:
        per-slot flat position index ``block_id * block + offset`` — the
        engine owns the page arithmetic; idle slots point at the reserved
        null page), then flash-decode the token's query over the pages.
        ``h`` is ``(b, 1, hidden)``; the caches are layer-stacked
        ``(L, num_blocks, kv_heads, block, head_dim)`` (block in the
        sublane dim — serve/cache.py layout) and scan ys rebuild them
        updated. ``attend_lengths`` includes the token just written
        (0 = idle slot, output exactly 0)."""
        from apex_tpu.ops.flash_decode import flash_decode

        c = self.cfg

        def body(h, xs):
            p, kp, vp = xs
            blk = kp.shape[2]
            bi, off = write_flat // blk, write_flat % blk
            x = self._ln(p["ln1"], h)
            q, k, v = self._qkv_heads(p["qkv"], x,
                                      positions=positions[:, None])
            # advanced indices split by the head slice land in front:
            # kp[bi, :, off] is (b, kv_heads, d), matching the new heads
            kp = kp.at[bi, :, off].set(k[:, :, 0, :].astype(kp.dtype))
            vp = vp.at[bi, :, off].set(v[:, :, 0, :].astype(vp.dtype))
            attn = flash_decode(
                q[:, :, 0, :], kp, vp, block_tables, attend_lengths,
                window=c.attention_window, impl=c.attention_impl)
            h = h + self._attn_out(p, attn[:, :, None, :])
            h = h + self._serve_ffn(p, self._ln(p["ln2"], h))
            return h, (kp, vp)

        h, (kps, vps) = lax.scan(body, h, (layers, k_pages, v_pages))
        return h, kps, vps

    def serve_layers_multi(self, layers: Params, h: jax.Array,
                           k_pages: jax.Array, v_pages: jax.Array,
                           block_tables: jax.Array, write_flat: jax.Array,
                           attend_lengths: jax.Array,
                           positions: jax.Array):
        """K-token sibling of :meth:`serve_layers_decode`: per layer, write
        K new tokens' k/v heads per slot into the paged cache (``write_flat``
        ``(b, K)`` flat position indices; masked rows point at the null
        page), then K-query flash-decode over the pages with TRAILING-query
        semantics (``attend_lengths[b]`` = keys visible to the FINAL query;
        query ``j`` sees ``attend_lengths[b] - (K-1-j)`` — in-chunk
        causality by length arithmetic). ``h`` is ``(b, K, hidden)``,
        ``positions`` ``(b, K)``. Drives both chunked prefill (one slot, K
        = chunk) and speculative verify (every slot, K = drafts + 1) from
        the same compiled structure."""
        from apex_tpu.ops.flash_decode import flash_decode_multi

        c = self.cfg

        def body(h, xs):
            p, kp, vp = xs
            blk = kp.shape[2]
            bi, off = write_flat // blk, write_flat % blk
            x = self._ln(p["ln1"], h)
            q, k, v = self._qkv_heads(p["qkv"], x, positions=positions)
            # (b, nh, K, d) -> (b, K, nh, d): kp[bi, :, off] is
            # (b, K, kv_heads, d) with the (b, K) advanced indices in front
            kp = kp.at[bi, :, off].set(
                k.transpose(0, 2, 1, 3).astype(kp.dtype))
            vp = vp.at[bi, :, off].set(
                v.transpose(0, 2, 1, 3).astype(vp.dtype))
            attn = flash_decode_multi(
                q, kp, vp, block_tables, attend_lengths,
                window=c.attention_window, impl=c.attention_impl)
            h = h + self._attn_out(p, attn)
            h = h + self._serve_ffn(p, self._ln(p["ln2"], h))
            return h, (kp, vp)

        h, (kps, vps) = lax.scan(body, h, (layers, k_pages, v_pages))
        return h, kps, vps

    def serve_head(self, params: Params, h: jax.Array) -> jax.Array:
        """Final LN + tied LM head returning FULL-vocab logits on every
        rank: under TP the vocab-sharded logits all-gather over the model
        axis (the mappings.py conjugate), so argmax/sampling is one
        consistent decision everywhere — the serving replacement for the
        training head's sharded-logit + vocab-parallel-CE pair."""
        c = self.cfg
        with jax.named_scope("head"):
            x = self._ln(params["ln_f"], h)
            wte = params["embedding"]["embedding"].astype(x.dtype)  # (V/tp, H)
            if c.axis is not None:
                x = tp.copy_to_tensor_model_parallel_region(x, c.axis)
            logits = jnp.einsum("bsh,vh->bsv", x, wte)
            if c.axis is not None:
                logits = tp.gather_from_tensor_model_parallel_region(
                    logits, c.axis)
            return logits

    def loss(self, params, tokens, targets, dropout_key=None,
             layer_chunk_meta=None) -> jax.Array:
        """Mean per-token loss — the fwd_step_func contract
        (schedules/common.py:196-255 loss reduction)."""
        return jnp.mean(self.apply(params, tokens, targets, dropout_key,
                                   layer_chunk_meta=layer_chunk_meta))
