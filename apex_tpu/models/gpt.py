"""Megatron-style GPT (reference: apex/transformer/testing/standalone_gpt.py:236-1517).

The reference vendors a full Megatron GPT (ParallelMLP, ParallelAttention,
ParallelTransformer, Embedding, GPTModel) as the test/benchmark vehicle for
its transformer framework. This is the TPU-native counterpart, built from
apex_tpu.transformer.tensor_parallel layers:

- token embedding: ``VocabParallelEmbedding`` (+ learned positions);
- per layer: LN → fused-QKV ``ColumnParallelLinear`` (no gather; output laid
  out ``(heads, 3, head_dim)`` so a TP shard holds whole heads, the layout
  contract of ParallelAttention, standalone_gpt.py:560-640) → flash attention
  on local heads → ``RowParallelLinear`` projection → residual → LN →
  column/row MLP with GeLU → residual;
- final LN → tied vocab-parallel LM head → ``vocab_parallel_cross_entropy``.

TPU-first structural choices (vs the reference's per-layer nn.ModuleList):

- layer parameters are **stacked** on a leading ``(num_layers, ...)`` dim and
  the stack is driven by ``lax.scan`` — one traced layer body regardless of
  depth (compile time O(1) in layers), and the natural shape for pipeline
  stages to slice;
- activation checkpointing is ``jax.checkpoint`` on the scanned body
  (reference: tensor_parallel/random.py:224-294 CheckpointFunction);
- dropout randomness comes from an explicit key, split per layer and folded
  per TP rank where state must differ (random.py:174-191 semantics).

Serial (``axis=None``) and shard_map-parallel execution use the same params
and the same code path, like the rest of the framework.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.ops.layer_norm import layer_norm as fused_layer_norm_op
from apex_tpu.parallel.mesh import AXIS_MODEL
from apex_tpu.transformer import tensor_parallel as tp

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    """Model hyperparameters (reference: testing/arguments.py essentials)."""

    vocab_size: int = 50304
    hidden_size: int = 1024
    num_layers: int = 24
    num_attention_heads: int = 16
    max_seq_len: int = 1024
    ffn_hidden_size: Optional[int] = None  # default 4*hidden
    axis: Optional[str] = AXIS_MODEL  # tensor-parallel mesh axis (None=serial)
    params_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    hidden_dropout: float = 0.1
    init_method_std: float = 0.02
    remat: bool = True  # activation checkpointing per layer
    attention_impl: str = "auto"  # flash_attention impl switch

    @property
    def ffn(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


class GPTModel:
    """Functional GPT with TP-sharded params (GPTModel, standalone_gpt.py:1361+).

    ``init(key)`` → full param tree; ``specs()`` → PartitionSpec tree;
    ``apply(params, tokens, targets=..., dropout_key=...)`` → per-token loss
    (or logits when ``targets`` is None). ``embed`` / ``run_layers`` /
    ``head`` expose the stage boundaries pipeline schedules need (the
    functional replacement for the reference's pre_process/post_process
    flags and set_input_tensor, pipeline_parallel/schedules/common.py:24-112).
    """

    def __init__(self, config: GPTConfig):
        self.cfg = config
        c = config
        if c.hidden_size % c.num_attention_heads:
            raise ValueError("hidden_size must divide evenly into heads")
        init = tp.scaled_normal(c.init_method_std)
        # Megatron scales output-layer init by 1/sqrt(2L)
        # (standalone_gpt.py scaled_init_method_normal).
        out_init = tp.scaled_normal(c.init_method_std / (2 * c.num_layers) ** 0.5)
        self.embedding = tp.VocabParallelEmbedding(
            c.vocab_size, c.hidden_size, axis=c.axis,
            params_dtype=c.params_dtype, init_method=init,
        )
        self.qkv = tp.ColumnParallelLinear(
            c.hidden_size, 3 * c.hidden_size, axis=c.axis, gather_output=False,
            params_dtype=c.params_dtype, init_method=init,
        )
        self.proj = tp.RowParallelLinear(
            c.hidden_size, c.hidden_size, axis=c.axis, input_is_parallel=True,
            params_dtype=c.params_dtype, init_method=out_init,
        )
        self.fc1 = tp.ColumnParallelLinear(
            c.hidden_size, c.ffn, axis=c.axis, gather_output=False,
            params_dtype=c.params_dtype, init_method=init,
        )
        self.fc2 = tp.RowParallelLinear(
            c.ffn, c.hidden_size, axis=c.axis, input_is_parallel=True,
            params_dtype=c.params_dtype, init_method=out_init,
        )

    # -- parameters ---------------------------------------------------------

    def _ln_init(self) -> Params:
        c = self.cfg
        return {
            "scale": jnp.ones((c.hidden_size,), c.params_dtype),
            "bias": jnp.zeros((c.hidden_size,), c.params_dtype),
        }

    def init(self, key: jax.Array) -> Params:
        c = self.cfg
        keys = jax.random.split(key, 4)
        pos = tp.scaled_normal(c.init_method_std)(
            keys[1], (c.max_seq_len, c.hidden_size), c.params_dtype
        )

        def layer_params(k) -> Params:
            ks = jax.random.split(k, 4)
            return {
                "ln1": self._ln_init(),
                "qkv": self.qkv.init(ks[0]),
                "proj": self.proj.init(ks[1]),
                "ln2": self._ln_init(),
                "fc1": self.fc1.init(ks[2]),
                "fc2": self.fc2.init(ks[3]),
            }

        layer_keys = jax.random.split(keys[2], c.num_layers)
        # Stack per-layer trees along a leading num_layers dim (vmap over
        # init is the cleanest way to build the scan-shaped stack).
        layers = jax.vmap(layer_params)(layer_keys)
        return {
            "embedding": self.embedding.init(keys[0]),
            "position": pos,
            "layers": layers,
            "ln_f": self._ln_init(),
        }

    def specs(self) -> Params:
        ln = {"scale": P(), "bias": P()}

        def stack(spec_tree):
            return jax.tree.map(
                lambda s: P(None, *s), spec_tree,
                is_leaf=lambda x: isinstance(x, P),
            )

        return {
            "embedding": self.embedding.specs(),
            "position": P(),
            "layers": {
                "ln1": stack(ln),
                "qkv": stack(self.qkv.specs()),
                "proj": stack(self.proj.specs()),
                "ln2": stack(ln),
                "fc1": stack(self.fc1.specs()),
                "fc2": stack(self.fc2.specs()),
            },
            "ln_f": ln,
        }

    # -- stages -------------------------------------------------------------

    def _ln(self, p: Params, x: jax.Array) -> jax.Array:
        # Mixed-dtype fused LN: bf16 activations, fp32 γβ
        # (MixedFusedLayerNorm, fused_layer_norm.py:398-436).
        return fused_layer_norm_op(x, p["scale"], p["bias"])

    def embed(self, params: Params, tokens: jax.Array) -> jax.Array:
        c = self.cfg
        h = self.embedding.apply(params["embedding"], tokens)
        pos = params["position"][: tokens.shape[-1]]
        return (h + pos).astype(c.compute_dtype)

    def _attention(self, p: Params, h: jax.Array) -> jax.Array:
        c = self.cfg
        b, s, _ = h.shape
        qkv = self.qkv.apply(p["qkv"], h)  # (b, s, 3*H/tp)
        n_local = qkv.shape[-1] // (3 * c.head_dim)
        qkv = qkv.reshape(b, s, n_local, 3, c.head_dim).transpose(0, 2, 3, 1, 4)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (b, nh, s, d)
        attn = flash_attention(q, k, v, causal=True, impl=c.attention_impl)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, n_local * c.head_dim)
        return self.proj.apply(p["proj"], attn)

    def _mlp(self, p: Params, h: jax.Array) -> jax.Array:
        return self.fc2.apply(p["fc2"], jax.nn.gelu(self.fc1.apply(p["fc1"], h)))

    def _dropout(self, x, key, rank_unique: bool):
        c = self.cfg
        if key is None or c.hidden_dropout == 0.0:
            return x
        if rank_unique and c.axis is not None:
            key = tp.model_parallel_key(key, c.axis)
        keep = 1.0 - c.hidden_dropout
        mask = jax.random.bernoulli(key, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)

    def _layer(self, p: Params, h: jax.Array, key) -> jax.Array:
        k1, k2 = (None, None) if key is None else tuple(jax.random.split(key))
        # Post-residual dropout is replicated across TP ranks (same key);
        # the reference draws it from the default (data-parallel) RNG state.
        h = h + self._dropout(self._attention(p, self._ln(p["ln1"], h)), k1, False)
        h = h + self._dropout(self._mlp(p, self._ln(p["ln2"], h)), k2, False)
        return h

    def run_layers(
        self, layers: Params, h: jax.Array, dropout_key: Optional[jax.Array] = None
    ) -> jax.Array:
        """Scan the (stacked) layer params over the hidden state. ``layers``
        may be any contiguous slice of the stack — a pipeline stage's chunk."""
        n = jax.tree.leaves(layers)[0].shape[0]
        keys = None if dropout_key is None else jax.random.split(dropout_key, n)

        def body(h, xs):
            p, k = xs
            return self._layer(p, h, k), None

        if self.cfg.remat:
            body = jax.checkpoint(body, prevent_cse=False)
        h, _ = lax.scan(body, h, (layers, keys))
        return h

    def head(
        self, params: Params, h: jax.Array,
        targets: Optional[jax.Array] = None,
    ):
        """Final LN + tied LM head (+ per-token loss when targets given)
        (post_language_model_processing, standalone_gpt.py:1361+)."""
        c = self.cfg
        h = self._ln(params["ln_f"], h)
        wte = params["embedding"]["embedding"].astype(h.dtype)  # (V/tp, H)
        if c.axis is not None:
            h = tp.copy_to_tensor_model_parallel_region(h, c.axis)
        logits = jnp.einsum("bsh,vh->bsv", h, wte)  # vocab-sharded logits
        if targets is None:
            return logits
        return tp.vocab_parallel_cross_entropy(logits, targets, axis=c.axis)

    def apply(
        self,
        params: Params,
        tokens: jax.Array,
        targets: Optional[jax.Array] = None,
        dropout_key: Optional[jax.Array] = None,
    ):
        h = self.embed(params, tokens)
        h = self.run_layers(params["layers"], h, dropout_key)
        return self.head(params, h, targets)

    def loss(self, params, tokens, targets, dropout_key=None) -> jax.Array:
        """Mean per-token loss — the fwd_step_func contract
        (schedules/common.py:196-255 loss reduction)."""
        return jnp.mean(self.apply(params, tokens, targets, dropout_key))
