"""Chunked LM-head cross-entropy — fused tied-decode + softmax-CE.

Reference lineage: apex/contrib/xentropy saves logits+LSE instead of probs
to halve softmax-CE memory (softmax_xentropy.py:4-28). This op takes the
idea to the LM head itself: for ``loss = xent(h @ Wᵀ, targets)`` with a
large vocabulary, the ``(tokens, vocab)`` logits (and their cotangent) are
the dominant activation — 8192 x 50304 bf16 is ~0.8 GB per materialization.

TPU-native design: scan over vocab chunks with an **online logsumexp**
(running max/sum — the flash-attention trick applied to the vocab axis), so
peak memory is ``tokens x vocab/num_chunks``. The backward recomputes each
chunk's logits and accumulates

    dh  = Σ_c (g ⊙ p_c) @ W_c        - g ⊙ W[targets]
    dW_c = (g ⊙ p_c)ᵀ @ h            - scatter_add(targets ∈ c, g ⊙ h)

via a custom VJP — the same recompute-over-store tradeoff as the reference's
xentropy kernel, extended through the tied decode GEMM.

Serial (unsharded vocab) form; under tensor parallelism the vocab axis is
already sharded V/tp ways and ``vocab_parallel_cross_entropy`` applies —
chunking composes with it per shard if needed.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _chunked(wte: jax.Array, num_chunks: int) -> jax.Array:
    V, H = wte.shape
    if V % num_chunks:
        raise ValueError(f"vocab {V} not divisible by num_chunks {num_chunks}")
    return wte.reshape(num_chunks, V // num_chunks, H)


def _fwd_scan(h2d, wte_c, targets):
    """Online logsumexp + target-logit gather over vocab chunks. GEMMs run in
    the input dtype with fp32 accumulation (the MXU-native mode, matching
    the plain head's bf16 einsum numerics); only the logsumexp arithmetic is
    fp32."""
    N = h2d.shape[0]
    C, Vc, H = wte_c.shape

    def body(carry, xs):
        m, s, tlogit = carry
        w, c = xs
        logits = jnp.matmul(h2d, w.astype(h2d.dtype).T,
                            preferred_element_type=jnp.float32)  # (N, Vc)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        local = targets - c * Vc
        in_chunk = (local >= 0) & (local < Vc)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, Vc - 1)[:, None], axis=1)[:, 0]
        tlogit = jnp.where(in_chunk, picked, tlogit)
        return (m_new, s, tlogit), None

    init = (jnp.full((N,), -jnp.inf, jnp.float32), jnp.zeros((N,), jnp.float32),
            jnp.zeros((N,), jnp.float32))
    (m, s, tlogit), _ = lax.scan(body, init, (wte_c, jnp.arange(C)))
    lse = m + jnp.log(s)
    return lse, tlogit


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def lm_head_cross_entropy(
    h: jax.Array,
    wte: jax.Array,
    targets: jax.Array,
    num_chunks: int = 8,
) -> jax.Array:
    """Per-token ``xent(h @ wteᵀ, targets)`` without materializing logits.

    Args:
      h: ``(..., H)`` final hidden states.
      wte: ``(V, H)`` tied embedding / output matrix.
      targets: ``(...)`` int ids.
      num_chunks: vocab chunking factor (peak logits memory = V/num_chunks).
    """
    return _fwd(h, wte, targets, num_chunks)[0]


def _fwd(h, wte, targets, num_chunks):
    shape = targets.shape
    h2d = h.reshape(-1, h.shape[-1])
    t = targets.reshape(-1)
    lse, tlogit = _fwd_scan(h2d, _chunked(wte, num_chunks), t)
    return (lse - tlogit).reshape(shape), (h, wte, t, lse)


def _bwd(num_chunks, res, g):
    h, wte, t, lse = res
    hshape = h.shape
    h2d = h.reshape(-1, hshape[-1])
    g32 = g.reshape(-1).astype(jnp.float32)
    wte_c = _chunked(wte, num_chunks)
    C, Vc, H = wte_c.shape
    gh = h2d.astype(jnp.float32) * g32[:, None]  # (N, H)

    def body(dh, xs):
        w, c = xs
        wt = w.astype(h2d.dtype)
        logits = jnp.matmul(h2d, wt.T, preferred_element_type=jnp.float32)
        p = jnp.exp(logits - lse[:, None])  # (N, Vc) softmax chunk, fp32
        gp = (p * g32[:, None]).astype(h2d.dtype)
        dh = dh + jnp.matmul(gp, wt, preferred_element_type=jnp.float32)
        dw = jnp.matmul(gp.T, h2d, preferred_element_type=jnp.float32)  # (Vc, H)
        # subtract the one-hot target rows that live in this chunk
        local = t - c * Vc
        in_chunk = (local >= 0) & (local < Vc)
        idx = jnp.where(in_chunk, local, Vc)  # Vc = drop row
        dw = dw.at[idx].add(-jnp.where(in_chunk[:, None], gh, 0.0), mode="drop")
        return dh, dw

    dh0 = -jnp.take(wte, t, axis=0).astype(jnp.float32) * g32[:, None]
    dh, dw_chunks = lax.scan(body, dh0, (wte_c, jnp.arange(C)))
    dwte = dw_chunks.reshape(C * Vc, H).astype(wte.dtype)
    return dh.reshape(hshape).astype(h.dtype), dwte, None


lm_head_cross_entropy.defvjp(_fwd, _bwd)


def lm_head_cross_entropy_reference(h, wte, targets):
    """Materialized ground truth for tests."""
    logits = h.astype(jnp.float32) @ wte.astype(jnp.float32).T
    lse = jax.nn.logsumexp(logits, axis=-1)
    tl = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return lse - tl
