"""Flash-decode — single-query attention over a PAGED KV cache.

The serving-side sibling of ops/flash_attention.py: at decode time each
sequence contributes ONE query that must attend every cached key, and the
keys live in fixed-size blocks of a preallocated page pool (apex_tpu/serve/
cache.py) addressed through a per-sequence block table — never in a
contiguous per-request buffer whose growth would recompile the step or
lane-pad per request. This is the split-KV decode primitive: the same
online-softmax recurrence as the streamed training kernels
(flash_attention._fwd_kernel), gridded over (batch, kv_head, page) with the
page index READ FROM THE BLOCK TABLE via Pallas scalar prefetch, so one
compiled program serves any mix of sequence lengths.

Reference: the fused single-pass attention of apex/contrib/fmha/fmha.py:33-74
(whose cu_seqlens contract is the per-sequence ``lengths`` here) — the paging
and the decode grid are beyond-reference capability, per the operation-fusion
framing of PAPERS.md (LLM inference acceleration via op fusion).

Layouts (the T(8,128) reasoning, PERF_NOTES r11 + the ISSUE 13 static-hbm
catch): pages are ``(num_blocks, kv_heads, block, head_dim)`` with head_dim
MINOR — the lane dim is head_dim (full vregs at d >= 128, the same
4x-pad-at-d-32 tax as training) — and the BLOCK SIZE second-minor, so the
sublane dim is a multiple of 8 by construction and the pool's padded
residency is the head_dim padding alone (the earlier kv_heads-second-minor
order padded 4 heads to 8 sublanes: 4x total at f32/h4/d64, static-hbm's
first real catch); a page never pays the 128x ``(.., 1)`` column tax the
lse tables were redesigned to avoid.

GQA-style head broadcasting: ``q`` carries ``H`` query heads over ``KH``
kv heads (``H % KH == 0``); each kernel program owns one kv head and its
``H/KH`` query-head group. ``window`` applies the causal sliding-window
convention of ``flash_attention`` (the decoding query sits at position
``length - 1``, so keys ``[length - window, length)`` are kept).

K-query extension (ISSUE 12): :func:`flash_decode_multi` attends K
TRAILING queries per sequence over the same pages — query ``j`` of slot
``b`` sits at position ``lengths[b] - K + j`` and sees exactly the keys a
sequential single-query decode would have seen at that position (in-chunk
causality falls out of the per-query length mask, since later in-chunk keys
hold larger positions). One program serves both chunked prefill (one slot,
C prompt positions per launch) and speculative verify (every slot, k
drafted tokens + the pending token in ONE batched shape-stable forward —
the whole-step operation fusion of PAPERS.md applied to decode).

No gradients: decode is inference-only (a custom VJP would re-gather pages;
training uses flash_attention).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.flash_attention import _NEG_INF, _NUM_LANES
from apex_tpu.ops.layer_norm import _interpret, _resolve_impl


def paged_attention_reference(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    block_tables: jax.Array,
    lengths: jax.Array,
    *,
    scale: Optional[float] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Unfused XLA twin of :func:`flash_decode` (the mha_reference analog):
    gather the pages dense, mask by length/window, one-pass softmax. The
    oracle the kernel is tested against, and the off-TPU default."""
    b, h, d = q.shape
    _, kh, blk, _ = k_pages.shape
    g = h // kh
    scale = (d ** -0.5) if scale is None else float(scale)
    s_max = block_tables.shape[1] * blk
    # (b, nb, kh, blk, d) -> (b, s_max, kh, d): positions contiguous
    k = k_pages[block_tables].transpose(0, 1, 3, 2, 4).reshape(
        b, s_max, kh, d)
    v = v_pages[block_tables].transpose(0, 1, 3, 2, 4).reshape(
        b, s_max, kh, d)
    qg = q.reshape(b, kh, g, d).astype(jnp.float32)
    s = jnp.einsum("bkgd,bskd->bkgs", qg, k.astype(jnp.float32)) * scale
    pos = jnp.arange(s_max, dtype=jnp.int32)
    valid = pos[None, :] < lengths[:, None]
    if window is not None:
        valid = valid & (pos[None, :] >= lengths[:, None] - window)
    s = jnp.where(valid[:, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # rows with no visible key (idle slots: length 0) output exactly 0,
    # matching the kernel's l == 0 guard and mha_reference's masked rows
    fully_masked = jnp.max(s, axis=-1, keepdims=True) <= _NEG_INF / 2
    p = jnp.where(fully_masked, 0.0, p)
    o = jnp.einsum("bkgs,bskd->bkgd", p, v.astype(jnp.float32))
    return o.reshape(b, h, d).astype(q.dtype)


def paged_attention_multi_reference(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    block_tables: jax.Array,
    lengths: jax.Array,
    *,
    scale: Optional[float] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Unfused XLA twin of :func:`flash_decode_multi`: gather the pages
    dense, mask per query by its own trailing position, one-pass softmax.
    ``q`` is ``(batch, heads, K, head_dim)``; query ``j`` sees
    ``lengths[b] - (K - 1 - j)`` keys."""
    b, h, kq, d = q.shape
    _, kh, blk, _ = k_pages.shape
    g = h // kh
    scale = (d ** -0.5) if scale is None else float(scale)
    s_max = block_tables.shape[1] * blk
    k = k_pages[block_tables].transpose(0, 1, 3, 2, 4).reshape(
        b, s_max, kh, d)
    v = v_pages[block_tables].transpose(0, 1, 3, 2, 4).reshape(
        b, s_max, kh, d)
    qg = q.reshape(b, kh, g, kq, d).astype(jnp.float32)
    s = jnp.einsum("bkgqd,bskd->bkgqs", qg,
                   k.astype(jnp.float32)) * scale
    pos = jnp.arange(s_max, dtype=jnp.int32)
    qlen = (lengths[:, None]
            - (kq - 1 - jnp.arange(kq, dtype=jnp.int32))[None, :])  # (b, K)
    valid = pos[None, None, :] < qlen[:, :, None]  # (b, K, s)
    if window is not None:
        valid = valid & (pos[None, None, :] >= qlen[:, :, None] - window)
    s = jnp.where(valid[:, None, None, :, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    fully_masked = jnp.max(s, axis=-1, keepdims=True) <= _NEG_INF / 2
    p = jnp.where(fully_masked, 0.0, p)
    o = jnp.einsum("bkgqs,bskd->bkgqd", p, v.astype(jnp.float32))
    return o.reshape(b, h, kq, d).astype(q.dtype)


def _decode_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale, blk, nb, window):
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[b]
    q = q_ref[0, 0].astype(jnp.float32) * scale  # (G, D)
    k = k_ref[0, 0].astype(jnp.float32)          # (blk, D)
    v = v_ref[0, 0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # (G, blk)
    pos = j * blk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    valid = pos < length
    if window is not None:
        valid = valid & (pos >= length - window)
    s = jnp.where(valid, s, _NEG_INF)

    m_prev = m_ref[:, 0:1]
    l_prev = l_ref[:, 0:1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    # fully-masked so far: exp(s - m) would be exp(0); zero the probs so l
    # stays 0 and the output stays 0 (same guard as _fwd_kernel)
    p = jnp.where(m_new <= _NEG_INF / 2, 0.0, jnp.exp(s - m_new))
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot(
        p, v, preferred_element_type=jnp.float32)
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nb - 1)
    def _done():
        l = l_ref[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def flash_decode(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    block_tables: jax.Array,
    lengths: jax.Array,
    *,
    scale: Optional[float] = None,
    window: Optional[int] = None,
    impl: str = "auto",
) -> jax.Array:
    """Single-query attention over a paged KV cache.

    Args:
      q: ``(batch, heads, head_dim)`` — one query per sequence slot (the
        token being decoded, already written to the cache so it attends
        itself; ``lengths`` includes it).
      k_pages, v_pages: ``(num_blocks, kv_heads, block, head_dim)`` page
        pools (apex_tpu.serve.cache layout: block in the sublane dim;
        ``heads % kv_heads == 0``, query-head groups broadcast over each
        kv head — GQA).
      block_tables: ``(batch, max_blocks)`` int32 — page ids per sequence,
        position ``p`` living in table slot ``p // block`` at offset
        ``p % block``. Slots beyond a sequence's allocation must point at
        a valid (e.g. the reserved null) page: trips are MASKED by
        ``lengths``, not skipped — the TPU grid is sequential, so the cost
        of a tick is O(max_blocks) DMA regardless of length (the price of
        one shape-stable program; see serve/engine.py).
      lengths: ``(batch,)`` int32 — valid keys per slot (0 = idle slot;
        its output is exactly 0).
      scale: score scale; defaults to ``1/sqrt(head_dim)``.
      window: causal sliding window — keep keys ``[length-window, length)``
        (the flash_attention convention seen from the newest position).
      impl: 'auto' | 'pallas' | 'xla' (auto = pallas on TPU, xla off —
        interpret mode keeps the Pallas path testable on CPU).

    Returns ``(batch, heads, head_dim)`` in ``q.dtype``.
    """
    b, h, d = q.shape
    n_pages, kh, blk, d2 = k_pages.shape
    if d2 != d or v_pages.shape != k_pages.shape:
        raise ValueError(
            f"page shapes {k_pages.shape}/{v_pages.shape} do not match "
            f"q head_dim {d}")
    if h % kh:
        raise ValueError(f"heads ({h}) must be a multiple of kv_heads ({kh})")
    if window is not None and int(window) < 1:
        raise ValueError(f"window must be a positive int, got {window}")
    nb = block_tables.shape[1]
    scale = (d ** -0.5) if scale is None else float(scale)
    use = _resolve_impl(impl)
    if use == "pallas" and (blk % 8 or d < 8):
        use = "xla"  # sub-tile pages: fall back like flash_attention does
    if use == "xla":
        return paged_attention_reference(
            q, k_pages, v_pages, block_tables, lengths,
            scale=scale, window=window)

    g = h // kh
    qg = q.reshape(b, kh, g, d)
    tables = block_tables.astype(jnp.int32)
    lens = lengths.astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kh, nb),
        in_specs=[
            pl.BlockSpec((1, 1, g, d), lambda bi, ki, j, tbl, ln: (bi, ki, 0, 0)),
            # the paged fetch: the PAGE index comes from the prefetched
            # block table, so the same compiled program walks any table
            # (page rows are (block, head_dim) — block in the sublane dim)
            pl.BlockSpec((1, 1, blk, d),
                         lambda bi, ki, j, tbl, ln: (tbl[bi, j], ki, 0, 0)),
            pl.BlockSpec((1, 1, blk, d),
                         lambda bi, ki, j, tbl, ln: (tbl[bi, j], ki, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, g, d),
                               lambda bi, ki, j, tbl, ln: (bi, ki, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, d), jnp.float32),
            pltpu.VMEM((g, _NUM_LANES), jnp.float32),
            pltpu.VMEM((g, _NUM_LANES), jnp.float32),
        ],
    )
    import functools

    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, blk=blk, nb=nb,
                          window=None if window is None else int(window)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kh, g, d), q.dtype),
        interpret=_interpret(),
    )(tables, lens, qg, k_pages, v_pages)
    return out.reshape(b, h, d)


def _decode_multi_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *, scale, blk, nb,
                         window, kq):
    """:func:`_decode_kernel` with K trailing queries per (batch, kv-head)
    program: the q block rows are ``(group, query)`` flattened with the
    query index MINOR, so row ``r``'s query index is ``r % K`` and its own
    visible-key count is ``length - (K - 1 - r % K)`` — the per-row length
    mask that realizes in-chunk causality."""
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[b]
    q = q_ref[0, 0].astype(jnp.float32) * scale  # (G*K, D)
    k = k_ref[0, 0].astype(jnp.float32)          # (blk, D)
    v = v_ref[0, 0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # (G*K, blk)
    pos = j * blk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    qi = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) % kq
    qlen = length - (kq - 1 - qi)
    valid = pos < qlen
    if window is not None:
        valid = valid & (pos >= qlen - window)
    s = jnp.where(valid, s, _NEG_INF)

    m_prev = m_ref[:, 0:1]
    l_prev = l_ref[:, 0:1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.where(m_new <= _NEG_INF / 2, 0.0, jnp.exp(s - m_new))
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot(
        p, v, preferred_element_type=jnp.float32)
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nb - 1)
    def _done():
        l = l_ref[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def flash_decode_multi(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    block_tables: jax.Array,
    lengths: jax.Array,
    *,
    scale: Optional[float] = None,
    window: Optional[int] = None,
    impl: str = "auto",
) -> jax.Array:
    """K-query attention over a paged KV cache (trailing-query semantics).

    Args:
      q: ``(batch, heads, K, head_dim)`` — K TRAILING queries per slot:
        query ``j`` sits at position ``lengths[b] - K + j`` (already
        written to the cache, so it attends itself) and sees exactly
        ``lengths[b] - (K - 1 - j)`` keys — the keys a sequential decode
        would have seen at that position. Chunked prefill drives this with
        one slot and K = chunk; speculative verify with every slot and
        K = drafts + 1.
      k_pages, v_pages, block_tables, lengths, scale, window, impl: as in
        :func:`flash_decode`; ``lengths[b]`` counts the keys visible to
        the FINAL query (0 = idle slot, all K outputs exactly 0).

    Returns ``(batch, heads, K, head_dim)`` in ``q.dtype``.
    """
    b, h, kq, d = q.shape
    n_pages, kh, blk, d2 = k_pages.shape
    if d2 != d or v_pages.shape != k_pages.shape:
        raise ValueError(
            f"page shapes {k_pages.shape}/{v_pages.shape} do not match "
            f"q head_dim {d}")
    if h % kh:
        raise ValueError(f"heads ({h}) must be a multiple of kv_heads ({kh})")
    if window is not None and int(window) < 1:
        raise ValueError(f"window must be a positive int, got {window}")
    nb = block_tables.shape[1]
    scale = (d ** -0.5) if scale is None else float(scale)
    use = _resolve_impl(impl)
    if use == "pallas" and (blk % 8 or d < 8):
        use = "xla"  # sub-tile pages: fall back like flash_attention does
    if use == "pallas" and (h // kh) * kq > 1024:
        # the kernel's scratch (acc (g*K, d) + m/l (g*K, lanes), all f32)
        # scales linearly with the query rows — past ~1k rows it crowds
        # VMEM; fall back to the dense path rather than fail Mosaic
        # (serve/engine.py clamps its chunk width below this)
        use = "xla"
    if use == "xla":
        return paged_attention_multi_reference(
            q, k_pages, v_pages, block_tables, lengths,
            scale=scale, window=window)

    g = h // kh
    # rows are (group, query) flattened with the query index MINOR — the
    # kernel recovers it as row % K
    qg = q.reshape(b, kh, g * kq, d)
    tables = block_tables.astype(jnp.int32)
    lens = lengths.astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kh, nb),
        in_specs=[
            pl.BlockSpec((1, 1, g * kq, d),
                         lambda bi, ki, j, tbl, ln: (bi, ki, 0, 0)),
            pl.BlockSpec((1, 1, blk, d),
                         lambda bi, ki, j, tbl, ln: (tbl[bi, j], ki, 0, 0)),
            pl.BlockSpec((1, 1, blk, d),
                         lambda bi, ki, j, tbl, ln: (tbl[bi, j], ki, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, g * kq, d),
                               lambda bi, ki, j, tbl, ln: (bi, ki, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g * kq, d), jnp.float32),
            pltpu.VMEM((g * kq, _NUM_LANES), jnp.float32),
            pltpu.VMEM((g * kq, _NUM_LANES), jnp.float32),
        ],
    )
    import functools

    out = pl.pallas_call(
        functools.partial(_decode_multi_kernel, scale=scale, blk=blk, nb=nb,
                          window=None if window is None else int(window),
                          kq=kq),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kh, g * kq, d), q.dtype),
        interpret=_interpret(),
    )(tables, lens, qg, k_pages, v_pages)
    return out.reshape(b, h, kq, d)
