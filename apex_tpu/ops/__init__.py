"""Pallas TPU kernels and XLA-fused op compositions.

TPU-native replacements for the reference's ``csrc/`` CUDA extensions
(SURVEY.md §2.2). Each op ships a lax/jnp reference path (used under
``interpret`` / CPU test meshes) and, where it pays, a Pallas TPU kernel.
"""

from apex_tpu.ops.multi_tensor import (  # noqa: F401
    tree_scale,
    tree_axpby,
    tree_l2norm,
    tree_l2norm_per_tensor,
    tree_nonfinite,
)
