"""Pallas TPU kernels and XLA-fused op compositions.

TPU-native replacements for the reference's ``csrc/`` CUDA extensions
(SURVEY.md §2.2). Each op ships a lax/jnp reference path (used under
``interpret`` / CPU test meshes) and, where it pays, a Pallas TPU kernel.
"""

from apex_tpu.ops.multi_tensor import (  # noqa: F401
    tree_scale,
    tree_axpby,
    tree_l2norm,
    tree_l2norm_per_tensor,
    tree_nonfinite,
)
# NOTE: the layer_norm/rms_norm *functions* are re-exported as fused_* to
# avoid shadowing the apex_tpu.ops.layer_norm submodule name.
from apex_tpu.ops.layer_norm import (  # noqa: F401
    layer_norm as fused_layer_norm,
    layer_norm_reference,
    rms_norm as fused_rms_norm,
    rms_norm_reference,
)
from apex_tpu.ops.softmax import (  # noqa: F401
    scaled_masked_softmax,
    scaled_masked_softmax_reference,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.ops.xentropy import (  # noqa: F401
    softmax_cross_entropy,
    softmax_cross_entropy_reference,
)
