"""Pallas TPU kernels and XLA-fused op compositions.

TPU-native replacements for the reference's ``csrc/`` CUDA extensions
(SURVEY.md §2.2). Each op ships a lax/jnp reference path (used under
``interpret`` / CPU test meshes) and, where it pays, a Pallas TPU kernel.
"""

from apex_tpu.ops.multi_tensor import (  # noqa: F401
    tree_scale,
    tree_axpby,
    tree_l2norm,
    tree_l2norm_per_tensor,
    tree_nonfinite,
)
# Kernel-level functional forms, exported as *_kernel: the reference-parity
# names fused_layer_norm/fused_rms_norm live in apex_tpu.normalization with
# the reference's (x, normalized_shape, eps) signature — re-exporting these
# (x, weight, bias, eps) functions under the same names was a foot-gun.
from apex_tpu.ops.layer_norm import (  # noqa: F401
    layer_norm as layer_norm_kernel,
    layer_norm_reference,
    rms_norm as rms_norm_kernel,
    rms_norm_reference,
)
from apex_tpu.ops.flash_decode import (  # noqa: F401
    flash_decode,
    paged_attention_reference,
)
from apex_tpu.ops.softmax import (  # noqa: F401
    scaled_masked_softmax,
    scaled_masked_softmax_reference,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.ops.xentropy import (  # noqa: F401
    softmax_cross_entropy,
    softmax_cross_entropy_reference,
)
