"""Fused scale + mask + softmax — Pallas TPU kernel with custom VJP.

Reference: csrc/megatron/scaled_masked_softmax.{cpp,h,cu} and
scaled_upper_triang_masked_softmax.{cpp,h,cu} (~1 500 LoC of warp-level
kernels) behind apex/transformer/functional/fused_softmax.py. Semantics:
``softmax(scale * x  [masked to -10000 where mask])`` over the last dim,
with a causal (upper-triangular) variant for GPT attention scores.

The CUDA kernels cap sk ≤ 2048 because a warp must hold the row
(scaled_masked_softmax.h:80-109); here the row lives in VMEM so the envelope
is ~64 K elements. Backward is the fused ``y * (g - Σ g·y)`` pass
(scaled_masked_softmax_cuda backward), saving only ``y`` like the reference.

Layout contract matches the reference: scores are ``(b, np, sq, sk)`` and an
optional boolean mask is ``(b, 1, sq, sk)`` broadcast over heads
(fused_softmax.py:67-92), True = masked out.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.layer_norm import _interpret, _resolve_impl

_MASK_FILL = -10000.0  # the reference's masked_fill value


def _q_block(sq: int, sk: int) -> int:
    target = max(1, (1 << 20) // max(1, sk * 4))
    blk = max(8, min(512, (target // 8) * 8))
    return min(blk, max(8, ((sq + 7) // 8) * 8))


def _softmax_fwd_kernel(x_ref, mask_ref, y_ref, *, scale, causal, blk_q):
    x = x_ref[...].astype(jnp.float32) * scale  # (1, 1|H, blk_q, sk)
    if mask_ref is not None:
        x = jnp.where(mask_ref[...], _MASK_FILL, x)
    if causal:
        qi = pl.program_id(2)  # blocks are always (1, 1|H, blk_q, sk)
        q_pos = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 2)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
        x = jnp.where(k_pos > q_pos, _MASK_FILL, x)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    y = e / jnp.sum(e, axis=-1, keepdims=True)
    y_ref[...] = y.astype(y_ref.dtype)


def _softmax_bwd_kernel(g_ref, y_ref, dx_ref, *, scale):
    g = g_ref[...].astype(jnp.float32)
    y = y_ref[...].astype(jnp.float32)
    dot = jnp.sum(g * y, axis=-1, keepdims=True)
    dx_ref[...] = (scale * y * (g - dot)).astype(dx_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "causal", "has_mask"))
def _fwd(x, mask, *, scale, causal, has_mask):
    b, h, sq, sk = x.shape
    blk_q = _q_block(sq, sk)
    pad = (-sq) % blk_q
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
        if has_mask:
            mask = jnp.pad(mask, ((0, 0), (0, 0), (0, pad), (0, 0)), constant_values=True)
    grid = (b, h, x.shape[2] // blk_q)

    x_spec = pl.BlockSpec(
        (1, 1, blk_q, sk), lambda i, j, q: (i, j, q, 0), memory_space=pltpu.VMEM
    )
    in_specs = [x_spec]
    args = [x]
    if has_mask:
        # mask is (b, 1, sq, sk) broadcast over heads — the reference layout
        # (fused_softmax.py:67-92) — or a full per-head (b, np, sq, sk).
        if mask.shape[1] == h:
            mask_idx = lambda i, j, q: (i, j, q, 0)
        elif mask.shape[1] == 1:
            mask_idx = lambda i, j, q: (i, 0, q, 0)
        else:
            raise ValueError(
                f"mask head dim must be 1 or {h}, got {mask.shape[1]}"
            )
        in_specs.append(
            pl.BlockSpec((1, 1, blk_q, sk), mask_idx, memory_space=pltpu.VMEM)
        )
        args.append(mask)

    def kernel(*refs):
        m_ref = refs[1] if has_mask else None
        _softmax_fwd_kernel(
            refs[0], m_ref, refs[-1], scale=scale, causal=causal, blk_q=blk_q
        )

    y = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=_interpret(),
    )(*args)
    return y[:, :, :sq] if pad else y


@functools.partial(jax.jit, static_argnames=("scale",))
def _bwd(g, y, *, scale):
    b, h, sq, sk = y.shape
    blk_q = _q_block(sq, sk)
    pad = (-sq) % blk_q
    if pad:
        g = jnp.pad(g, ((0, 0), (0, 0), (0, pad), (0, 0)))
        y = jnp.pad(y, ((0, 0), (0, 0), (0, pad), (0, 0)))
    grid = (b, h, y.shape[2] // blk_q)
    spec = pl.BlockSpec(
        (1, 1, blk_q, sk), lambda i, j, q: (i, j, q, 0), memory_space=pltpu.VMEM
    )
    dx = pl.pallas_call(
        functools.partial(_softmax_bwd_kernel, scale=scale),
        grid=grid,
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(y.shape, y.dtype),
        interpret=_interpret(),
    )(g, y)
    return dx[:, :, :sq] if pad else dx


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _scaled_masked_softmax(x, mask, scale, causal):
    return _fwd(x, mask, scale=scale, causal=causal, has_mask=mask is not None)


def _sms_fwd(x, mask, scale, causal):
    y = _fwd(x, mask, scale=scale, causal=causal, has_mask=mask is not None)
    return y, y


def _sms_bwd(scale, causal, y, g):
    return _bwd(g, y, scale=scale), None


_scaled_masked_softmax.defvjp(_sms_fwd, _sms_bwd)


def _xla_softmax(x, mask, scale, causal):
    x = x.astype(jnp.float32) * scale
    if mask is not None:
        x = jnp.where(mask, _MASK_FILL, x)
    if causal:
        sq, sk = x.shape[-2], x.shape[-1]
        q = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        x = jnp.where(k > q, _MASK_FILL, x)
    return jax.nn.softmax(x, axis=-1)


def scaled_masked_softmax(
    x: jax.Array,
    mask: Optional[jax.Array] = None,
    scale: float = 1.0,
    *,
    causal: bool = False,
    impl: str = "auto",
) -> jax.Array:
    """``softmax(scale*x masked to -10000)`` over sk
    (ScaledMaskedSoftmax, fused_softmax.py:67-92). ``causal=True`` composes
    the upper-triangular mask with the boolean mask in one fused pass —
    the decoder-with-padding case the reference's two separate kernels
    cannot express together."""
    if _resolve_impl(impl) == "xla":
        return _xla_softmax(x, mask, scale, causal=causal).astype(x.dtype)
    return _scaled_masked_softmax(x, mask, float(scale), bool(causal))


def scaled_upper_triang_masked_softmax(
    x: jax.Array, scale: float = 1.0, *, impl: str = "auto"
) -> jax.Array:
    """Causal variant (ScaledUpperTriangMaskedSoftmax, fused_softmax.py:21-46)."""
    if _resolve_impl(impl) == "xla":
        return _xla_softmax(x, None, scale, causal=True).astype(x.dtype)
    return _scaled_masked_softmax(x, None, float(scale), True)


def scaled_masked_softmax_reference(x, mask=None, scale=1.0, causal=False):
    """Pure-XLA ground truth (the torch-softmax fallback path,
    fused_softmax.py:176-199)."""
    return _xla_softmax(x, mask, scale, causal).astype(x.dtype)
