"""Tree-level fused tensor ops — the multi_tensor_apply equivalent.

The reference batches elementwise updates over lists of tensors into single
CUDA launches via ``apex.multi_tensor_apply`` + ``amp_C`` kernels
(reference: apex/multi_tensor_apply/multi_tensor_apply.py:3-30,
csrc/multi_tensor_apply.cuh:16-133, csrc/multi_tensor_scale_kernel.cu,
csrc/multi_tensor_axpby_kernel.cu, csrc/multi_tensor_l2norm_kernel.cu).

On TPU the launch-batching problem does not exist: a ``jax.tree.map`` inside a
jitted function is traced into one XLA program and fused by the compiler, so
these helpers express only the *semantics* — scaling with non-finite
detection, axpby grad accumulation, and global/per-tensor L2 norms — as pure
functions over pytrees.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp


def _float_leaves(tree):
    # matches jax arrays, numpy arrays, and python/np floats alike
    leaves = [jnp.asarray(l) for l in jax.tree.leaves(tree)]
    return [l for l in leaves if jnp.issubdtype(l.dtype, jnp.inexact)]


def tree_nonfinite(tree) -> jax.Array:
    """Return a scalar bool: any non-finite value anywhere in the tree.

    The ``noop_flag`` / ``found_inf`` signal of the reference kernels
    (csrc/multi_tensor_scale_kernel.cu overflow path; apex/amp/scaler.py:6-31).
    """
    leaves = _float_leaves(tree)
    if not leaves:
        return jnp.asarray(False)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(l.astype(jnp.float32)))) for l in leaves]
    return jnp.stack(flags).any()


def tree_scale(tree, scale, out_dtype=None) -> Tuple[Any, jax.Array]:
    """``out = in * scale`` over a pytree, plus overflow flag.

    Equivalent of ``amp_C.multi_tensor_scale`` (csrc/multi_tensor_scale_kernel.cu):
    the amp unscale and master<->model copy primitive. Returns
    ``(scaled_tree, found_inf)`` where found_inf reflects non-finites in the
    *input* (so an overflow in grads is detected even if scaling maps it to 0).
    """
    found_inf = tree_nonfinite(tree)

    def _scale(l):
        l = jnp.asarray(l)
        if not jnp.issubdtype(l.dtype, jnp.inexact):
            return l
        out = l.astype(jnp.float32) * scale
        return out.astype(out_dtype or l.dtype)

    return jax.tree.map(_scale, tree), found_inf


def tree_axpby(a, x_tree, b, y_tree, out_dtype=None) -> Tuple[Any, jax.Array]:
    """``out = a*x + b*y`` elementwise over two pytrees + overflow flag.

    Equivalent of ``amp_C.multi_tensor_axpby``
    (csrc/multi_tensor_axpby_kernel.cu), used by the reference to merge
    stashed gradient accumulators (apex/amp/_process_optimizer.py:161-202).
    """
    found_inf = jnp.logical_or(tree_nonfinite(x_tree), tree_nonfinite(y_tree))

    def _axpby(x, y):
        x = jnp.asarray(x)
        if not jnp.issubdtype(x.dtype, jnp.inexact):
            return x
        out = a * x.astype(jnp.float32) + b * jnp.asarray(y).astype(jnp.float32)
        return out.astype(out_dtype or x.dtype)

    return jax.tree.map(_axpby, x_tree, y_tree), found_inf


def tree_l2norm(tree) -> jax.Array:
    """Global L2 norm across every leaf (csrc/multi_tensor_l2norm_kernel.cu).

    Used for LAMB's global grad norm (apex/optimizers/fused_lamb.py:108-136)
    and gradient clipping.
    """
    leaves = _float_leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return jnp.sqrt(sq)


def tree_l2norm_per_tensor(tree):
    """Per-leaf L2 norms, same treedef (the ``per_tensor`` kernel output).

    Used by NovoGrad's per-tensor second moments
    (apex/optimizers/fused_novograd.py) and LAMB trust ratios.
    """
    return jax.tree.map(
        lambda l: jnp.sqrt(jnp.sum(jnp.square(jnp.asarray(l).astype(jnp.float32))))
        if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)
        else l,
        tree,
    )


def tree_clip_by_global_norm(tree, max_norm: float):
    """Clip a grad tree to a global-norm budget (FP16_Optimizer.clip_master_grads,
    apex/fp16_utils/fp16_optimizer.py:386-407)."""
    gnorm = tree_l2norm(tree)
    factor = jnp.minimum(1.0, max_norm / (gnorm + 1e-6))

    def _clip(l):
        l = jnp.asarray(l)
        if not jnp.issubdtype(l.dtype, jnp.inexact):
            return l
        return (l * factor).astype(l.dtype)

    return jax.tree.map(_clip, tree), gnorm
