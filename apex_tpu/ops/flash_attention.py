"""Flash attention — blockwise fused attention as a Pallas TPU kernel.

This one kernel family subsumes three of the reference's CUDA extensions
(SURVEY.md §2.2): ``fmhalib`` (flash-style fused MHA, fp16 seq ≤ 512, SM80 —
apex/contrib/fmha/fmha.py:33-74), ``fast_multihead_attn`` (fused self/encdec
attention, apex/contrib/multihead_attn/), and the two Megatron fused-softmax
kernels (csrc/megatron/scaled_(upper_triang_)masked_softmax.h, sk ≤ 2048)
whose job was to keep the score matrix out of HBM. Blockwise online softmax
(the published FlashAttention recurrence) never materializes scores at all,
and has no 512/2048 sequence cap — the envelope is VMEM, and beyond that the
``context``-axis ring attention (apex_tpu.transformer.ring) tiles over chips.

Layout: ``(batch, heads, seq, head_dim)`` — the reference's score layout
``(b, np, sq, sk)`` (fused_softmax.py:67-92) with head_dim restored.

Forward saves only O and the per-row logsumexp; backward recomputes scores
blockwise (the fmha/FlashAttention memory plan) in two passes: one gridded
over q-blocks for dQ, one over k-blocks for dK/dV.

Masking: ``causal=True`` for the upper-triangular variant, and/or an additive
``bias`` broadcastable to ``(b, h, sq, sk)`` (the additive-mask path of
fast_multihead_attn; boolean masks become ``-10000`` biases upstream, matching
the reference's masked_fill value).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.layer_norm import _interpret, _resolve_impl

_NEG_INF = -1e30


def _pick_block(n: int, target: int) -> int:
    """Largest multiple-of-8 divisor of n that is <= target (n if none)."""
    best = None
    for cand in range(min(n, target), 7, -1):
        if n % cand == 0 and cand % 8 == 0:
            best = cand
            break
    return best if best is not None else n


def _supported(sq: int, sk: int, d: int) -> bool:
    """Shapes the Pallas path handles without padding: 8-aligned seqs.

    The analog of the reference's ``is_kernel_available`` envelope
    (fused_softmax.py:151-171) — unsupported shapes fall back to the XLA
    path, like the reference falls back to torch softmax."""
    return sq % 8 == 0 and sk % 8 == 0 and d >= 8


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, b_ref, off_ref, o_ref, lse_ref, *, scale, causal, blk_q, blk_k):
    q = q_ref[0, 0].astype(jnp.float32) * scale  # (blk_q, d)
    sk = k_ref.shape[2]
    d = q.shape[-1]
    qi = pl.program_id(2)
    nk = sk // blk_k
    # Global-position offsets of this q/k shard (ring attention over the
    # ``context`` axis passes the shard's start positions so causal masking
    # is correct across sequence shards; 0 for unsharded attention).
    q_off = off_ref[0] if off_ref is not None else 0
    k_off = off_ref[1] if off_ref is not None else 0

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, 0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (blk_q, blk_k)
        if b_ref is not None:
            s = s + b_ref[0, 0, :, pl.ds(j * blk_k, blk_k)].astype(jnp.float32)
        if causal:
            q_pos = q_off + qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = k_off + j * blk_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(k_pos > q_pos, _NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    acc = jnp.zeros((blk_q, d), jnp.float32)
    m0 = jnp.full((blk_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((blk_q, 1), jnp.float32)
    if causal:
        # skip k-blocks strictly above the diagonal (fully masked): the
        # triangular-work saving the reference's upper-triang kernel gets
        # from its tiling (scaled_upper_triang_masked_softmax.h).
        lim = (q_off - k_off + (qi + 1) * blk_q + blk_k - 1) // blk_k
        nk = jnp.clip(lim, 0, nk)
    acc, m, l = jax.lax.fori_loop(0, nk, body, (acc, m0, l0))
    # Fully-masked rows (possible with an all -inf bias row) have l == 0.
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l_safe)


# ---------------------------------------------------------------------------
# Backward: dQ pass (grid over q-blocks), then dK/dV pass (grid over k-blocks)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, b_ref, off_ref, do_ref, lse_ref, delta_ref, dq_ref, db_ref,
    *, scale, causal, blk_q, blk_k, b_bcast, h_bcast, dims,
):
    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]
    sk = k_ref.shape[2]
    # dims maps logical (b, h, q) grid coordinates to program_id positions —
    # _flash_bwd orders the grid so dbias revisits are *consecutive*.
    qi = pl.program_id(dims["q"])
    nk = sk // blk_k
    q_off = off_ref[0] if off_ref is not None else 0
    k_off = off_ref[1] if off_ref is not None else 0

    if db_ref is not None:
        # A bias broadcast over batch/heads maps several grid steps onto the
        # same dbias block. Pallas only keeps an output window live across
        # consecutive same-index steps, so the broadcast dims iterate
        # innermost (see _dq_grid_order); zero on the first visit, then
        # accumulate.
        conds = []
        if b_bcast:
            conds.append(pl.program_id(dims["b"]) == 0)
        if h_bcast:
            conds.append(pl.program_id(dims["h"]) == 0)
        if conds:
            pred = conds[0]
            for c in conds[1:]:
                pred = pred & c

            @pl.when(pred)
            def _zero():
                db_ref[0, 0] = jnp.zeros_like(db_ref[0, 0])

        else:
            db_ref[0, 0] = jnp.zeros_like(db_ref[0, 0])

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if b_ref is not None:
            s = s + b_ref[0, 0, :, pl.ds(j * blk_k, blk_k)].astype(jnp.float32)
        if causal:
            q_pos = q_off + qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = k_off + j * blk_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(k_pos > q_pos, _NEG_INF, s)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        if db_ref is not None:
            cur = db_ref[0, 0, :, pl.ds(j * blk_k, blk_k)]
            db_ref[0, 0, :, pl.ds(j * blk_k, blk_k)] = cur + ds
        return dq + scale * jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    if causal:
        lim = (q_off - k_off + (qi + 1) * blk_q + blk_k - 1) // blk_k
        nk = jnp.clip(lim, 0, nk)
    dq = jax.lax.fori_loop(0, nk, body, jnp.zeros_like(q))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, b_ref, off_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, scale, causal, blk_q, blk_k,
):
    k = k_ref[0, 0].astype(jnp.float32)  # (blk_k, d)
    v = v_ref[0, 0].astype(jnp.float32)
    sq = q_ref.shape[2]
    ki = pl.program_id(2)
    nq = sq // blk_q
    q_off = off_ref[0] if off_ref is not None else 0
    k_off = off_ref[1] if off_ref is not None else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(i * blk_q, blk_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(i * blk_q, blk_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * blk_q, blk_q), :]
        delta = delta_ref[0, 0, pl.ds(i * blk_q, blk_q), :]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (blk_q, blk_k)
        if b_ref is not None:
            s = s + b_ref[0, 0, pl.ds(i * blk_q, blk_q), :].astype(jnp.float32)
        if causal:
            q_pos = q_off + i * blk_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = k_off + ki * blk_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(k_pos > q_pos, _NEG_INF, s)
        p = jnp.exp(s - lse)  # (blk_q, blk_k)
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dk_new = dk + scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk_new, dv_new

    dk0 = jnp.zeros_like(k)
    dv0 = jnp.zeros_like(v)
    # Under causal masking, q-blocks entirely left of this k-block's diagonal
    # contribute nothing — start at the first intersecting block.
    start = jnp.clip((k_off - q_off + ki * blk_k) // blk_q, 0, nq) if causal else 0
    dk, dv = jax.lax.fori_loop(start, nq, body, (dk0, dv0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------


def _bias_spec(bias, blk_q, sk):
    """BlockSpec for an additive bias of shape (b|1, h|1, sq, sk), for grids
    ordered (b, h, q). Size-1 batch/head dims pin the index map to 0; size-1
    sq/sk dims are canonicalized away by ``flash_attention`` (broadcast_to)
    before the custom_vjp boundary, so they never reach here.
    """
    bb, bh = bias.shape[0], bias.shape[1]

    def idx(bi, hi, qi):
        return (bi if bb > 1 else 0, hi if bh > 1 else 0, qi, 0)

    return pl.BlockSpec((1, 1, blk_q, sk), idx, memory_space=pltpu.VMEM)


def _dq_grid_order(bias, b_bcast, h_bcast):
    """Logical-(b, h, q) → grid-position order for the dQ pass.

    dbias blocks are revisited across the broadcast dims, and Pallas output
    windows persist only across *consecutive* same-index steps — so whichever
    dims collapse in the dbias index map must iterate innermost."""
    if bias is None:
        return ("b", "h", "q")
    if b_bcast and not h_bcast:
        return ("q", "h", "b")
    return ("q", "b", "h")  # h broadcast, or both, or neither


def _offsets_spec():
    """SMEM spec for the (q_off, k_off) global-position scalars."""
    return pl.BlockSpec((2,), lambda *_: (0,), memory_space=pltpu.SMEM)


@functools.partial(
    jax.jit, static_argnames=("scale", "causal", "blk_q", "blk_k")
)
def _flash_fwd(q, k, v, bias, offsets, *, scale, causal, blk_q, blk_k):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    grid = (b, h, sq // blk_q)
    qspec = pl.BlockSpec((1, 1, blk_q, d), lambda bi, hi, qi: (bi, hi, qi, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0),
                         memory_space=pltpu.VMEM)
    ospec = qspec
    lspec = pl.BlockSpec((1, 1, blk_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0),
                         memory_space=pltpu.VMEM)
    in_specs = [qspec, kspec, kspec]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(_bias_spec(bias, blk_q, sk))
        args.append(bias)
    if offsets is not None:
        in_specs.append(_offsets_spec())
        args.append(offsets)
    has_bias, has_off = bias is not None, offsets is not None

    def kern(*refs):
        refs = list(refs)
        qr, kr, vr = refs[:3]
        i = 3
        br = refs[i] if has_bias else None
        i += has_bias
        offr = refs[i] if has_off else None
        i += has_off
        orf, lr = refs[i], refs[i + 1]
        _fwd_kernel(qr, kr, vr, br, offr, orf, lr,
                    scale=scale, causal=causal, blk_q=blk_q, blk_k=blk_k)

    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[ospec, lspec],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    # Named for selective activation checkpointing: a remat policy saving
    # these (e.g. GPTConfig.remat_policy="save_attn") keeps the kernel's
    # output + logsumexp so backward never re-runs the forward kernel —
    # O(b*h*s*d) memory buys back the most expensive recompute in the layer.
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, lse


@functools.partial(
    jax.jit, static_argnames=("scale", "causal", "blk_q", "blk_k")
)
def _flash_bwd(q, k, v, bias, offsets, o, lse, do, *, scale, causal, blk_q, blk_k):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1,
                    keepdims=True)  # (b, h, sq, 1)

    # dQ pass: grid over (b, h, q-blocks), reordered so dbias accumulation
    # over broadcast dims happens on consecutive steps (see _dq_grid_order);
    # also emits dS accumulated into dbias.
    b_bcast = bias is not None and bias.shape[0] == 1
    h_bcast = bias is not None and bias.shape[1] == 1
    order = _dq_grid_order(bias, b_bcast, h_bcast)
    dims = {name: pos for pos, name in enumerate(order)}
    sizes = {"b": b, "h": h, "q": sq // blk_q}
    grid = tuple(sizes[name] for name in order)

    def reorder(fn):
        """Wrap a logical (bi, hi, qi) index map for the reordered grid."""

        def idx(*a):
            return fn(a[dims["b"]], a[dims["h"]], a[dims["q"]])

        return idx

    qspec = pl.BlockSpec((1, 1, blk_q, d), reorder(lambda bi, hi, qi: (bi, hi, qi, 0)),
                         memory_space=pltpu.VMEM)
    kfull = pl.BlockSpec((1, 1, sk, d), reorder(lambda bi, hi, qi: (bi, hi, 0, 0)),
                         memory_space=pltpu.VMEM)
    lblk = pl.BlockSpec((1, 1, blk_q, 1), reorder(lambda bi, hi, qi: (bi, hi, qi, 0)),
                        memory_space=pltpu.VMEM)

    in_specs = [qspec, kfull, kfull]
    args = [q, k, v]
    if bias is not None:
        bb, bh = bias.shape[0], bias.shape[1]
        in_specs.append(pl.BlockSpec(
            (1, 1, blk_q, sk),
            reorder(lambda bi, hi, qi: (bi if bb > 1 else 0, hi if bh > 1 else 0, qi, 0)),
            memory_space=pltpu.VMEM,
        ))
        args.append(bias)
    if offsets is not None:
        in_specs.append(_offsets_spec())
        args.append(offsets)
    in_specs += [qspec, lblk, lblk]
    args += [do, lse, delta]
    has_bias, has_off = bias is not None, offsets is not None

    def dq_kern(*refs):
        refs = list(refs)
        qr, kr, vr = refs[:3]
        i = 3
        br = refs[i] if has_bias else None
        i += has_bias
        offr = refs[i] if has_off else None
        i += has_off
        dor, lr, dr, dqr = refs[i:i + 4]
        dbr = refs[i + 4] if has_bias else None
        _bwd_dq_kernel(qr, kr, vr, br, offr, dor, lr, dr, dqr, dbr,
                       scale=scale, causal=causal, blk_q=blk_q, blk_k=blk_k,
                       b_bcast=b_bcast, h_bcast=h_bcast, dims=dims)

    out_specs = [qspec]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    if bias is not None:
        out_specs.append(pl.BlockSpec(
            (1, 1, blk_q, sk),
            reorder(lambda bi, hi, qi: (bi if bb > 1 else 0, hi if bh > 1 else 0, qi, 0)),
            memory_space=pltpu.VMEM,
        ))
        out_shape.append(jax.ShapeDtypeStruct(bias.shape, jnp.float32))
    res = pl.pallas_call(
        dq_kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(*args)
    dq, dbias = (res[0], res[1]) if bias is not None else (res[0], None)

    # dK/dV pass: grid over k blocks; q/do/lse/delta stream in full.
    qfull = pl.BlockSpec((1, 1, sq, d), lambda bi, hi, ki: (bi, hi, 0, 0),
                         memory_space=pltpu.VMEM)
    kblk = pl.BlockSpec((1, 1, blk_k, d), lambda bi, hi, ki: (bi, hi, ki, 0),
                        memory_space=pltpu.VMEM)
    lfull = pl.BlockSpec((1, 1, sq, 1), lambda bi, hi, ki: (bi, hi, 0, 0),
                         memory_space=pltpu.VMEM)
    in_specs2 = [qfull, kblk, kblk]
    args2 = [q, k, v]
    if bias is not None:
        bb, bh = bias.shape[0], bias.shape[1]
        bspec2 = pl.BlockSpec(
            (1, 1, sq, blk_k),
            lambda bi, hi, ki: (bi if bb > 1 else 0, hi if bh > 1 else 0, 0, ki),
            memory_space=pltpu.VMEM,
        )
        in_specs2.append(bspec2)
        args2.append(bias)
    if offsets is not None:
        in_specs2.append(_offsets_spec())
        args2.append(offsets)
    in_specs2 += [qfull, lfull, lfull]
    args2 += [do, lse, delta]

    def dkv_kern(*refs):
        refs = list(refs)
        qr, kr, vr = refs[:3]
        i = 3
        br = refs[i] if has_bias else None
        i += has_bias
        offr = refs[i] if has_off else None
        i += has_off
        dor, lr, dr, dkr, dvr = refs[i:i + 5]
        _bwd_dkv_kernel(qr, kr, vr, br, offr, dor, lr, dr, dkr, dvr,
                        scale=scale, causal=causal, blk_q=blk_q, blk_k=blk_k)

    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(b, h, sk // blk_k),
        in_specs=in_specs2,
        out_specs=[kblk, kblk],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=_interpret(),
    )(*args2)
    return dq, dk, dv, dbias


# ---------------------------------------------------------------------------
# custom_vjp + public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, bias, scale, causal, blk_q, blk_k):
    o, _ = _flash_fwd(q, k, v, bias, None, scale=scale, causal=causal,
                      blk_q=blk_q, blk_k=blk_k)
    return o


def _flash_vjp_fwd(q, k, v, bias, scale, causal, blk_q, blk_k):
    o, lse = _flash_fwd(q, k, v, bias, None, scale=scale, causal=causal,
                        blk_q=blk_q, blk_k=blk_k)
    return o, (q, k, v, bias, o, lse)


def _flash_vjp_bwd(scale, causal, blk_q, blk_k, res, do):
    q, k, v, bias, o, lse = res
    dq, dk, dv, dbias = _flash_bwd(q, k, v, bias, None, o, lse, do, scale=scale,
                                   causal=causal, blk_q=blk_q, blk_k=blk_k)
    if dbias is not None:
        dbias = dbias.astype(bias.dtype)
    return dq, dk, dv, dbias


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def mha_reference(
    q: jax.Array, k: jax.Array, v: jax.Array,
    bias: Optional[jax.Array] = None,
    *, causal: bool = False, scale: Optional[float] = None,
) -> jax.Array:
    """Unfused XLA attention (the torch-softmax fallback path,
    fused_softmax.py:193-199 forward_torch_softmax equivalent)."""
    d = q.shape[-1]
    scale = (d ** -0.5) if scale is None else scale
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        q_pos = jnp.arange(sq)[:, None]
        k_pos = jnp.arange(sk)[None, :]
        s = jnp.where(k_pos > q_pos, _NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
    impl: str = "auto",
) -> jax.Array:
    """Fused multi-head attention.

    Args:
      q, k, v: ``(batch, heads, seq, head_dim)``; kv seq may differ from q seq
        (encoder-decoder attention, apex/contrib/multihead_attn encdec path).
      bias: optional additive bias broadcastable to ``(b, h, sq, sk)``
        (additive-mask attention; use -10000 for masked positions like the
        reference's masked_fill).
      causal: upper-triangular masking (scaled_upper_triang_masked_softmax).
      scale: score scale; defaults to 1/sqrt(head_dim).
      impl: 'auto' | 'pallas' | 'xla'.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = (d ** -0.5) if scale is None else float(scale)
    use = _resolve_impl(impl)
    if use == "pallas" and not _supported(sq, sk, d):
        use = "xla"
    if use == "xla":
        return mha_reference(q, k, v, bias, causal=causal, scale=scale)
    blk_q = _pick_block(sq, block_q)
    blk_k = _pick_block(sk, block_k)
    if bias is not None:
        if bias.ndim != 4:
            raise ValueError(f"bias must be rank-4 broadcastable, got shape {bias.shape}")
        # Canonicalize size-1 sq/sk dims away (the kernels tile dims 2/3 at
        # full size). This sits outside the custom_vjp, so AD of broadcast_to
        # sums dbias back to the caller's original shape.
        bb, bh = bias.shape[0], bias.shape[1]
        if bb not in (1, b) or bh not in (1, h):
            raise ValueError(f"bias shape {bias.shape} not broadcastable to "
                             f"({b}, {h}, {sq}, {sk})")
        bias = jnp.broadcast_to(bias, (bb, bh, sq, sk))
    return _flash(q, k, v, bias, scale, bool(causal), blk_q, blk_k)
