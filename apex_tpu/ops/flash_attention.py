"""Flash attention — blockwise fused attention as a Pallas TPU kernel.

This one kernel family subsumes three of the reference's CUDA extensions
(SURVEY.md §2.2): ``fmhalib`` (flash-style fused MHA, fp16 seq ≤ 512, SM80 —
apex/contrib/fmha/fmha.py:33-74), ``fast_multihead_attn`` (fused self/encdec
attention, apex/contrib/multihead_attn/), and the two Megatron fused-softmax
kernels (csrc/megatron/scaled_(upper_triang_)masked_softmax.h, sk ≤ 2048)
whose job was to keep the score matrix out of HBM. Blockwise online softmax
(the published FlashAttention recurrence) never materializes scores at all,
and has no 512/2048 sequence cap — the envelope is VMEM, and beyond that the
``context``-axis ring attention (apex_tpu.transformer.ring) tiles over chips.

Layout: ``(batch, heads, seq, head_dim)`` — the reference's score layout
``(b, np, sq, sk)`` (fused_softmax.py:67-92) with head_dim restored.

Forward saves only O and the per-row logsumexp; backward recomputes scores
blockwise (the fmha/FlashAttention memory plan) in two passes: one gridded
over q-blocks for dQ, one over k-blocks for dK/dV.

Masking: ``causal=True`` for the upper-triangular variant, and/or an additive
``bias`` broadcastable to ``(b, h, sq, sk)`` (the additive-mask path of
fast_multihead_attn; boolean masks become ``-10000`` biases upstream, matching
the reference's masked_fill value), and/or ``segment_ids`` — packed-varlen
attention (the reference fmha's cu_seqlens contract, fmha.py:33-74): tokens
attend only within their segment, and for the contiguous (non-decreasing-ids)
layout the kernel SKIPS score blocks whose q/k segment ranges cannot
intersect, so a batch of short sequences pays ~sum(len_i^2) FLOPs instead of
the padded total^2 — the entire point of the reference's packed kernel.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.layer_norm import _interpret, _resolve_impl

_NEG_INF = -1e30
# TPU vreg geometry: segment ids ride in a lane-major layout (q ids
# replicated over lanes, kv ids over sublanes) so the in-kernel equality
# test is a plain vector compare — the standard Pallas idiom.
_NUM_LANES = 128
_NUM_SUBLANES = 8


def _pick_block(n: int, target: int, mult: int = 8) -> int:
    """Largest multiple-of-``mult`` divisor of n that is <= target (n if
    none)."""
    best = None
    for cand in range(min(n, target), mult - 1, -1):
        if n % cand == 0 and cand % mult == 0:
            best = cand
            break
    return best if best is not None else n


def _supported(sq: int, sk: int, d: int) -> bool:
    """Shapes the Pallas path handles without padding: 8-aligned seqs.

    The analog of the reference's ``is_kernel_available`` envelope
    (fused_softmax.py:151-171) — unsupported shapes fall back to the XLA
    path, like the reference falls back to torch softmax."""
    return sq % 8 == 0 and sk % 8 == 0 and d >= 8


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _apply_pos_masks(s, causal, window, q_base, k_base):
    """Causal and/or sliding-window masking of a score block, in GLOBAL
    positions (``q_base``/``k_base`` are the block's first row/column
    positions including any ring-attention shard offset, so the window is
    correct across context-parallel sequence shards).

    ``window=w`` keeps, for each query position p, the keys in
    ``[p-w+1, p]`` when causal (the Mistral/Longformer sliding-window
    convention: w attended positions including self) and the symmetric
    band ``[p-w+1, p+w-1]`` when not. No reference counterpart — the
    reference's fmha/fused-softmax kernels have no local-attention mode;
    this is the standard long-context pairing for the streamed kernels
    (O(s·w) score work instead of O(s²))."""
    if not causal and window is None:
        return s
    q_pos = q_base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = k_base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if causal:
        s = jnp.where(k_pos > q_pos, _NEG_INF, s)
    if window is not None:
        s = jnp.where(q_pos - k_pos >= window, _NEG_INF, s)
        if not causal:
            s = jnp.where(k_pos - q_pos >= window, _NEG_INF, s)
    return s


def _dense_pos_masks(s, q_pos, k_pos, causal, window, neg=_NEG_INF):
    """The XLA-path twin of :func:`_apply_pos_masks` (shared by
    ``mha_reference`` and the ring's ``_partial_attn_xla``): same causal +
    window semantics on a dense score tensor with broadcastable position
    arrays instead of in-kernel iotas."""
    if causal:
        s = jnp.where(k_pos > q_pos, neg, s)
    if window is not None:
        s = jnp.where(q_pos - k_pos >= window, neg, s)
        if not causal:
            s = jnp.where(k_pos - q_pos >= window, neg, s)
    return s


def _window_k_range(lo, hi, qi, blk_q, blk_k, q_off, k_off, causal, window):
    """Clip the k-block loop range [lo, hi) for a q block under a sliding
    window: k blocks wholly left of the window's trailing edge (and, when
    not causal, wholly right of its leading edge) are never computed —
    the block-skip that makes window cost O(s·w). Floor division keeps
    the bounds conservative for partially-covered blocks."""
    if window is None:
        return lo, hi
    t = q_off - k_off + qi * blk_q - window + 1  # min valid local k_pos
    lo = jnp.maximum(lo, t // blk_k)
    if not causal:
        u = q_off - k_off + (qi + 1) * blk_q + window - 2  # max valid
        hi = jnp.clip(u // blk_k + 1, 0, hi)
    return lo, hi


def _window_q_range(lo, hi, ki, blk_q, blk_k, q_off, k_off, causal, window):
    """The dK/dV-pass mirror of :func:`_window_k_range`: clip the q-block
    loop range [lo, hi) for a k block."""
    if window is None:
        return lo, hi
    u = k_off - q_off + (ki + 1) * blk_k + window - 2  # max valid local q_pos
    hi = jnp.clip(u // blk_q + 1, 0, hi)
    if not causal:
        t = k_off - q_off + ki * blk_k - window + 1
        lo = jnp.maximum(lo, t // blk_q)
    return lo, hi


def _window_grid(blk_outer, blk_inner, n_inner, causal, window,
                 inner_is_k=True):
    """Window-restricted inner grid dimension for the STREAMED kernels.

    The TPU grid is sequential — trips cannot be skipped, so with the
    plain (nq, nk) grid a window saves MXU/VPU work but still pays the
    DMA and trip overhead of every block pair: O(s²) traffic for O(s·w)
    math (measured: 256k-token windowed training was trip-bound). This
    helper instead shrinks the inner grid extent to the band's worst-case
    block width and returns ``(width, base)`` where ``base(outer_idx)``
    maps a trip to its first global inner block — used both by the
    BlockSpec index maps (clamped, so DMA stays in bounds) and inside the
    kernels (unclamped, so the existing [lo, hi) predicate skips the
    clamped-over trips). Only usable when positions are statically known
    (no ring ``offsets``: index maps see program ids only, not operands).

    ``inner_is_k``: inner dim iterates k blocks for a q block (fwd/dQ);
    False for the dK/dV pass (q blocks for a k block), where the causal
    band extends FORWARD from the diagonal instead of backward."""
    if window is None:
        return None
    # the band spans the outer block plus (window-1) on the trailing side,
    # plus another (window-1) leading when bidirectional; under causal the
    # trailing side is behind the diagonal for k-inner (fwd/dQ) but AHEAD
    # of it for q-inner (dK/dV), which only moves the band's start:
    #   k-inner: k_pos ∈ [q_pos - window + 1, q_pos | q_pos + window - 1]
    #   q-inner: q_pos ∈ [k_pos | k_pos - window + 1, k_pos + window - 1]
    span = (blk_outer - 1) + (window - 1) + (0 if causal else (window - 1))
    back = 0 if (causal and not inner_is_k) else window - 1

    def base(oi):
        return (oi * blk_outer - back) // blk_inner

    width = span // blk_inner + 2  # +1 block-misalignment, +1 conservative
    if width >= n_inner:
        return None  # the band covers (nearly) everything: keep the full grid
    return width, base


def _lse_group(nq):
    """Row-group size for the dense (b, h, nq, blk_q) lse/delta tables.

    Groups of 8 rows keep the in-VMEM window at 8·blk_q·4 bytes no matter
    the sequence length (the whole-table window is sq·4 bytes, which blew
    the 16 MB scoped-VMEM limit at 1M tokens); 8 divides every large
    power-of-two nq, and the whole-table fallback only triggers for small
    odd nq where the table is tiny anyway. The second-minor block dim must
    be a multiple of 8 or the full dim — both branches satisfy that."""
    return 8 if nq % 8 == 0 and nq >= 8 else nq


def _window_grid_maps(blk_outer, blk_inner, n_inner, causal, window, offsets,
                      inner_is_k=True):
    """Shared unpack of :func:`_window_grid` for the three streamed
    pallas_calls: returns ``(extent, base, index_map)`` where ``extent``
    is the inner grid dimension, ``base`` feeds the kernel's trip→block
    remap (None = unrestricted), and ``index_map(outer, inner)`` is the
    CLAMPED block index for the BlockSpecs (edge trips fetch a clamped
    block; the kernels' [lo, hi) predicate never reads it)."""
    wg = _window_grid(blk_outer, blk_inner, n_inner, causal, window,
                      inner_is_k) if offsets is None else None
    if wg is None:
        return n_inner, None, (lambda oi, ij: ij)
    extent, base = wg
    return extent, base, (
        lambda oi, ij: jnp.clip(base(oi) + ij, 0, n_inner - 1))


def _seg_mask(s, q_ids, ks_ref, j, blk_k, pad_id):
    """Mask ``s`` (blk_q, blk_k) to -inf where the q/k segment ids differ
    (or the key is padding). ``q_ids`` is the lane-replicated (blk_q, 128)
    tile; kv ids arrive sublane-replicated (slices of (SUBLANES, sk))."""
    q_col = jnp.tile(q_ids, (1, s.shape[-1] // _NUM_LANES))
    k_ids = ks_ref[0, 0:1, pl.ds(j * blk_k, blk_k)]
    valid = q_col == k_ids
    if pad_id is not None:
        valid = valid & (k_ids != pad_id)
    return jnp.where(valid, s, _NEG_INF)


def _seg_mask_if_needed(s, qs_ref, ks_ref, kmm_ref, j_meta, j_slice, blk_k,
                        pad_id, qmin, qmax):
    """Apply the segment mask only on blocks that need it — the splash-
    attention full/partial block distinction: an interior block whose q and
    k segment ranges are the same single (non-pad) segment is fully valid,
    so the mask (the dominant vector cost of the segment path) is skipped
    via a real branch. ``kmm_ref`` holds per-k-block (min, max) ids in SMEM.

    ``j_meta`` indexes the per-block metadata (always the global k-block
    number); ``j_slice`` indexes into ``ks_ref``, which holds the whole
    sk in the resident layout (j_slice == j_meta) but only the current
    block in the streamed layout (j_slice == 0)."""
    kmin = kmm_ref[0, 0, j_meta]
    kmax = kmm_ref[0, 1, j_meta]
    uniform_ok = (qmin == qmax) & (kmin == kmax) & (kmin == qmin)
    if pad_id is not None:
        uniform_ok = uniform_ok & (qmin != pad_id)
    return jax.lax.cond(
        uniform_ok,
        lambda s: s,
        lambda s: _seg_mask(s, qs_ref[0], ks_ref, j_slice, blk_k, pad_id),
        s,
    )


def _fwd_kernel(q_ref, k_ref, v_ref, b_ref, qs_ref, ks_ref, kmm_ref, bnd_ref,
                off_ref, o_ref, lse_ref, *, scale, causal, blk_q, blk_k,
                pad_id, window=None, lse_group=1):
    q = q_ref[0, 0].astype(jnp.float32) * scale  # (blk_q, d)
    sk = k_ref.shape[2]
    d = q.shape[-1]
    qi = pl.program_id(2)
    nk = sk // blk_k
    # Global-position offsets of this q/k shard (ring attention over the
    # ``context`` axis passes the shard's start positions so causal masking
    # is correct across sequence shards; 0 for unsharded attention).
    q_off = off_ref[0] if off_ref is not None else 0
    k_off = off_ref[1] if off_ref is not None else 0
    if qs_ref is not None:
        # this q block's segment-id range, once per program
        qmin = jnp.min(qs_ref[0])
        qmax = jnp.max(qs_ref[0])

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, 0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (blk_q, blk_k)
        if b_ref is not None:
            s = s + b_ref[0, 0, :, pl.ds(j * blk_k, blk_k)].astype(jnp.float32)
        if qs_ref is not None:
            s = _seg_mask_if_needed(s, qs_ref, ks_ref, kmm_ref, j, j, blk_k,
                                    pad_id, qmin, qmax)
        s = _apply_pos_masks(s, causal, window, q_off + qi * blk_q,
                             k_off + j * blk_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # fully-masked rows keep m == -inf: exp(s - m) would be exp(0);
        # zero their probabilities so l stays 0 and the output stays 0
        p = jnp.where(m_new <= _NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    acc = jnp.zeros((blk_q, d), jnp.float32)
    m0 = jnp.full((blk_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((blk_q, 1), jnp.float32)
    lo = 0
    if bnd_ref is not None:
        # contiguous-segment block bounds (precomputed host-side): k blocks
        # outside [lo, hi) cannot share a segment with this q block — the
        # packed-varlen FLOP saving (sum len_i^2, not total^2)
        lo = bnd_ref[0, 0, qi]
        nk = jnp.minimum(nk, bnd_ref[0, 1, qi])
    if causal:
        # skip k-blocks strictly above the diagonal (fully masked): the
        # triangular-work saving the reference's upper-triang kernel gets
        # from its tiling (scaled_upper_triang_masked_softmax.h).
        lim = (q_off - k_off + (qi + 1) * blk_q + blk_k - 1) // blk_k
        nk = jnp.clip(lim, 0, nk)
    lo, nk = _window_k_range(lo, nk, qi, blk_q, blk_k, q_off, k_off,
                             causal, window)
    acc, m, l = jax.lax.fori_loop(lo, nk, body, (acc, m0, l0))
    # Fully-masked rows (padding segments, all -inf bias rows) have l == 0.
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    # lse rides in the dense (b, h, nq, blk_q) table layout (grouped rows;
    # see _flash_fwd_stream's note — the (b, h, sq, 1) shape lane-pads
    # 128x at the custom-call boundary)
    lse_ref[0, 0, pl.ds(qi % lse_group, 1), :] = jnp.transpose(
        m + jnp.log(l_safe), (1, 0))


# ---------------------------------------------------------------------------
# Backward: dQ pass (grid over q-blocks), then dK/dV pass (grid over k-blocks)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, b_ref, qs_ref, ks_ref, kmm_ref, bnd_ref, off_ref,
    do_ref, lse_ref, delta_ref, dq_ref, db_ref,
    *, scale, causal, blk_q, blk_k, pad_id, b_bcast, h_bcast, dims,
    window=None, lse_group=1,
):
    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    sk = k_ref.shape[2]
    # dims maps logical (b, h, q) grid coordinates to program_id positions —
    # _flash_bwd orders the grid so dbias revisits are *consecutive*.
    qi = pl.program_id(dims["q"])
    # dense (b, h, nq, blk_q) table layout (see _flash_fwd_stream)
    lse = jnp.transpose(lse_ref[0, 0, pl.ds(qi % lse_group, 1), :], (1, 0))
    delta = jnp.transpose(delta_ref[0, 0, pl.ds(qi % lse_group, 1), :],
                          (1, 0))
    nk = sk // blk_k
    q_off = off_ref[0] if off_ref is not None else 0
    k_off = off_ref[1] if off_ref is not None else 0
    if qs_ref is not None:
        qmin = jnp.min(qs_ref[0])
        qmax = jnp.max(qs_ref[0])

    if db_ref is not None:
        # A bias broadcast over batch/heads maps several grid steps onto the
        # same dbias block. Pallas only keeps an output window live across
        # consecutive same-index steps, so the broadcast dims iterate
        # innermost (see _dq_grid_order); zero on the first visit, then
        # accumulate.
        conds = []
        if b_bcast:
            conds.append(pl.program_id(dims["b"]) == 0)
        if h_bcast:
            conds.append(pl.program_id(dims["h"]) == 0)
        if conds:
            pred = conds[0]
            for c in conds[1:]:
                pred = pred & c

            @pl.when(pred)
            def _zero():
                db_ref[0, 0] = jnp.zeros_like(db_ref[0, 0])

        else:
            db_ref[0, 0] = jnp.zeros_like(db_ref[0, 0])

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if b_ref is not None:
            s = s + b_ref[0, 0, :, pl.ds(j * blk_k, blk_k)].astype(jnp.float32)
        if qs_ref is not None:
            s = _seg_mask_if_needed(s, qs_ref, ks_ref, kmm_ref, j, j, blk_k,
                                    pad_id, qmin, qmax)
        s = _apply_pos_masks(s, causal, window, q_off + qi * blk_q,
                             k_off + j * blk_k)
        # fully-masked rows carry lse == -inf; exp(s - lse) would be exp(0)
        p = jnp.where(lse <= _NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        if db_ref is not None:
            cur = db_ref[0, 0, :, pl.ds(j * blk_k, blk_k)]
            db_ref[0, 0, :, pl.ds(j * blk_k, blk_k)] = cur + ds
        return dq + scale * jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    lo = 0
    if bnd_ref is not None:
        lo = bnd_ref[0, 0, qi]
        nk = jnp.minimum(nk, bnd_ref[0, 1, qi])
    if causal:
        lim = (q_off - k_off + (qi + 1) * blk_q + blk_k - 1) // blk_k
        nk = jnp.clip(lim, 0, nk)
    lo, nk = _window_k_range(lo, nk, qi, blk_q, blk_k, q_off, k_off,
                             causal, window)
    dq = jax.lax.fori_loop(lo, nk, body, jnp.zeros_like(q))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, b_ref, qs_ref, ks_ref, qmm_ref, kmm_ref, bnd_ref,
    off_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, scale, causal, blk_q, blk_k, pad_id, window=None,
):
    k = k_ref[0, 0].astype(jnp.float32)  # (blk_k, d)
    v = v_ref[0, 0].astype(jnp.float32)
    sq = q_ref.shape[2]
    ki = pl.program_id(2)
    nq = sq // blk_q
    q_off = off_ref[0] if off_ref is not None else 0
    k_off = off_ref[1] if off_ref is not None else 0
    if qs_ref is not None:
        # this k block's segment-id range, once per program (SMEM metadata)
        kmin = kmm_ref[0, 0, ki]
        kmax = kmm_ref[0, 1, ki]

    def seg_mask_dkv(s, i):
        q_ids = jnp.tile(qs_ref[0, pl.ds(i * blk_q, blk_q), :],
                         (1, blk_k // _NUM_LANES))
        k_ids = ks_ref[0, 0:1, pl.ds(ki * blk_k, blk_k)]
        valid = q_ids == k_ids
        if pad_id is not None:
            valid = valid & (k_ids != pad_id)
        return jnp.where(valid, s, _NEG_INF)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(i * blk_q, blk_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(i * blk_q, blk_q), :].astype(jnp.float32)
        # dense (b, h, nq, blk_q) tables, full-resident here (sq·4 bytes —
        # 64x less VMEM than the lane-padded (sq, 1) windows they replace)
        lse = jnp.transpose(lse_ref[0, 0, pl.ds(i, 1), :], (1, 0))
        delta = jnp.transpose(delta_ref[0, 0, pl.ds(i, 1), :], (1, 0))
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (blk_q, blk_k)
        if b_ref is not None:
            s = s + b_ref[0, 0, pl.ds(i * blk_q, blk_q), :].astype(jnp.float32)
        if qs_ref is not None:
            qmin = qmm_ref[0, 0, i]
            qmax = qmm_ref[0, 1, i]
            uniform_ok = (qmin == qmax) & (kmin == kmax) & (kmin == qmin)
            if pad_id is not None:
                uniform_ok = uniform_ok & (qmin != pad_id)
            s = jax.lax.cond(uniform_ok, lambda s: s,
                             lambda s: seg_mask_dkv(s, i), s)
        s = _apply_pos_masks(s, causal, window, q_off + i * blk_q,
                             k_off + ki * blk_k)
        # fully-masked rows carry lse == -inf; exp(s - lse) would be exp(0)
        p = jnp.where(lse <= _NEG_INF / 2, 0.0, jnp.exp(s - lse))  # (blk_q, blk_k)
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dk_new = dk + scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk_new, dv_new

    dk0 = jnp.zeros_like(k)
    dv0 = jnp.zeros_like(v)
    # Under causal masking, q-blocks entirely left of this k-block's diagonal
    # contribute nothing — start at the first intersecting block.
    start = jnp.clip((k_off - q_off + ki * blk_k) // blk_q, 0, nq) if causal else 0
    if bnd_ref is not None:
        # contiguous-segment bounds over q blocks for this k block
        start = jnp.maximum(start, bnd_ref[0, 0, ki])
        nq = jnp.minimum(nq, bnd_ref[0, 1, ki])
    start, nq = _window_q_range(start, nq, ki, blk_q, blk_k, q_off, k_off,
                                causal, window)
    dk, dv = jax.lax.fori_loop(start, nq, body, (dk0, dv0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# Streamed kernels: the k-loop (q-loop for dK/dV) lives in the GRID, so K/V
# (resp. Q/dO) arrive in blk-sized tiles and VMEM residency is bounded by
# BLOCK sizes, not sequence length — the fix for the 16 MB wall the resident
# layout hits at s≈8k with segment operands (VERDICT r3 weak #3 / ADVICE
# medium). Online-softmax state (acc, m, l) persists across the inner grid
# dimension in VMEM scratch; outputs are written on the last inner step.
# Blocks outside the segment bounds / causal limit skip their compute via
# pl.when (the DMA still runs — on TPU the sequential grid cannot skip
# trips, so the packed saving here is MXU/VPU work, not bandwidth).
# Streamed mode supports causal + segment ids + ring offsets; dense bias
# stays on the resident path (a (sq, sk) bias at streaming sizes is the
# wrong tool — packed segment ids are the long-sequence masking story).
# ---------------------------------------------------------------------------


def _fwd_kernel_stream(q_ref, k_ref, v_ref, qs_ref, ks_ref, kmm_ref, qmm_ref,
                       bnd_ref, off_ref, o_ref, lse_ref, acc_ref, m_ref,
                       l_ref, *, scale, causal, blk_q, blk_k, pad_id, nk,
                       window=None, k_base=None, lse_group=1):
    qi = pl.program_id(2)
    kj_raw = pl.program_id(3)
    # window-restricted grid (_window_grid): trip kj_raw covers global k
    # block k_base(qi) + kj_raw; kb may fall outside [0, nk) on the band's
    # edge trips — the [lo, hi) predicate below skips those (their DMA
    # fetched a clamped block, never read)
    kj = k_base(qi) + kj_raw if k_base is not None else kj_raw
    q_off = off_ref[0] if off_ref is not None else 0
    k_off = off_ref[1] if off_ref is not None else 0

    @pl.when(kj_raw == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    lo = jnp.int32(0)
    hi = jnp.int32(nk)
    if bnd_ref is not None:
        lo = bnd_ref[0, 0, qi]
        hi = jnp.minimum(hi, bnd_ref[0, 1, qi])
    if causal:
        lim = (q_off - k_off + (qi + 1) * blk_q + blk_k - 1) // blk_k
        hi = jnp.clip(lim, 0, hi)
    lo, hi = _window_k_range(lo, hi, qi, blk_q, blk_k, q_off, k_off,
                             causal, window)

    @pl.when((kj >= lo) & (kj < hi))
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # (blk_q, d)
        k = k_ref[0, 0].astype(jnp.float32)  # (blk_k, d)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if qs_ref is not None:
            # per-block (min, max) ids from SMEM metadata, not a per-trip
            # VPU reduction over the (blk_q, 128) id tile
            qmin = qmm_ref[0, 0, qi]
            qmax = qmm_ref[0, 1, qi]
            s = _seg_mask_if_needed(s, qs_ref, ks_ref, kmm_ref, kj, 0, blk_k,
                                    pad_id, qmin, qmax)
        s = _apply_pos_masks(s, causal, window, q_off + qi * blk_q,
                             k_off + kj * blk_k)
        m = m_ref[...]
        l = l_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(m_new <= _NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        alpha = jnp.exp(m - m_new)
        l_ref[...] = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)

    @pl.when(kj_raw == pl.num_programs(3) - 1)
    def _finalize():
        l = l_ref[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        # lse rides in the DENSE (b, h, nq, blk_q) layout (see
        # _flash_bwd_stream): transpose this block's (blk_q, 1) column
        # into row qi of the per-head table (windowed in lse_group rows)
        lse_ref[0, 0, pl.ds(qi % lse_group, 1), :] = jnp.transpose(
            m_ref[...] + jnp.log(l_safe), (1, 0))


def _bwd_dq_kernel_stream(q_ref, k_ref, v_ref, qs_ref, ks_ref, kmm_ref,
                          qmm_ref, bnd_ref, off_ref, do_ref, lse_ref,
                          delta_ref, dq_ref, dq_acc_ref,
                          *, scale, causal, blk_q, blk_k, pad_id, nk,
                          window=None, k_base=None, lse_group=1):
    qi = pl.program_id(2)
    kj_raw = pl.program_id(3)
    kj = k_base(qi) + kj_raw if k_base is not None else kj_raw
    q_off = off_ref[0] if off_ref is not None else 0
    k_off = off_ref[1] if off_ref is not None else 0

    @pl.when(kj_raw == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    lo = jnp.int32(0)
    hi = jnp.int32(nk)
    if bnd_ref is not None:
        lo = bnd_ref[0, 0, qi]
        hi = jnp.minimum(hi, bnd_ref[0, 1, qi])
    if causal:
        lim = (q_off - k_off + (qi + 1) * blk_q + blk_k - 1) // blk_k
        hi = jnp.clip(lim, 0, hi)
    lo, hi = _window_k_range(lo, hi, qi, blk_q, blk_k, q_off, k_off,
                             causal, window)

    @pl.when((kj >= lo) & (kj < hi))
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        # dense-table layout: row qi of (nq, blk_q), reoriented to a
        # (blk_q, 1) column (see _flash_fwd_stream's lse note)
        lse = jnp.transpose(lse_ref[0, 0, pl.ds(qi % lse_group, 1), :],
                            (1, 0))
        delta = jnp.transpose(delta_ref[0, 0, pl.ds(qi % lse_group, 1), :],
                              (1, 0))
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if qs_ref is not None:
            qmin = qmm_ref[0, 0, qi]
            qmax = qmm_ref[0, 1, qi]
            s = _seg_mask_if_needed(s, qs_ref, ks_ref, kmm_ref, kj, 0, blk_k,
                                    pad_id, qmin, qmax)
        s = _apply_pos_masks(s, causal, window, q_off + qi * blk_q,
                             k_off + kj * blk_k)
        p = jnp.where(lse <= _NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_acc_ref[...] = dq_acc_ref[...] + scale * jax.lax.dot(
            ds, k, preferred_element_type=jnp.float32)

    @pl.when(kj_raw == pl.num_programs(3) - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel_stream(q_ref, k_ref, v_ref, qs_ref, ks_ref, qmm_ref,
                           kmm_ref, bnd_ref, off_ref, do_ref, lse_ref,
                           delta_ref, dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                           *, scale, causal, blk_q, blk_k, pad_id, nq,
                           window=None, q_base=None, lse_group=1):
    ki = pl.program_id(2)
    qi_raw = pl.program_id(3)
    qi = q_base(ki) + qi_raw if q_base is not None else qi_raw
    q_off = off_ref[0] if off_ref is not None else 0
    k_off = off_ref[1] if off_ref is not None else 0

    @pl.when(qi_raw == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    lo = jnp.int32(0)
    hi = jnp.int32(nq)
    if causal:
        lo = jnp.clip((k_off - q_off + ki * blk_k) // blk_q, 0, nq)
    if bnd_ref is not None:
        lo = jnp.maximum(lo, bnd_ref[0, 0, ki])
        hi = jnp.minimum(hi, bnd_ref[0, 1, ki])
    lo, hi = _window_q_range(lo, hi, ki, blk_q, blk_k, q_off, k_off,
                             causal, window)

    @pl.when((qi >= lo) & (qi < hi))
    def _compute():
        k = k_ref[0, 0].astype(jnp.float32)  # (blk_k, d)
        v = v_ref[0, 0].astype(jnp.float32)
        q = q_ref[0, 0].astype(jnp.float32)  # (blk_q, d)
        do = do_ref[0, 0].astype(jnp.float32)
        # dense-table layout; qi is the (possibly remapped) global q
        # block — in range whenever this trip computes (the predicate),
        # so the fetched group is the one containing it
        lse = jnp.transpose(lse_ref[0, 0, pl.ds(qi % lse_group, 1), :],
                            (1, 0))
        delta = jnp.transpose(delta_ref[0, 0, pl.ds(qi % lse_group, 1), :],
                              (1, 0))
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (blk_q, blk_k)
        if qs_ref is not None:
            # same classifier+mask as the fwd/dQ kernels: kmm indexed by
            # this kernel's global k block (ki), ks sliced at 0 (streamed
            # block layout), q range from the SMEM metadata
            qmin = qmm_ref[0, 0, qi]
            qmax = qmm_ref[0, 1, qi]
            s = _seg_mask_if_needed(s, qs_ref, ks_ref, kmm_ref, ki, 0,
                                    blk_k, pad_id, qmin, qmax)
        s = _apply_pos_masks(s, causal, window, q_off + qi * blk_q,
                             k_off + ki * blk_k)
        p = jnp.where(lse <= _NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dv_acc_ref[...] = dv_acc_ref[...] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc_ref[...] = dk_acc_ref[...] + scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi_raw == pl.num_programs(3) - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc_ref[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------


def _bias_spec(bias, blk_q, sk):
    """BlockSpec for an additive bias of shape (b|1, h|1, sq, sk), for grids
    ordered (b, h, q). Size-1 batch/head dims pin the index map to 0; size-1
    sq/sk dims are canonicalized away by ``flash_attention`` (broadcast_to)
    before the custom_vjp boundary, so they never reach here.
    """
    bb, bh = bias.shape[0], bias.shape[1]

    def idx(bi, hi, qi):
        return (bi if bb > 1 else 0, hi if bh > 1 else 0, qi, 0)

    return pl.BlockSpec((1, 1, blk_q, sk), idx, memory_space=pltpu.VMEM)


def _dq_grid_order(bias, b_bcast, h_bcast):
    """Logical-(b, h, q) → grid-position order for the dQ pass.

    dbias blocks are revisited across the broadcast dims, and Pallas output
    windows persist only across *consecutive* same-index steps — so whichever
    dims collapse in the dbias index map must iterate innermost."""
    if bias is None:
        return ("b", "h", "q")
    if b_bcast and not h_bcast:
        return ("q", "h", "b")
    return ("q", "b", "h")  # h broadcast, or both, or neither


def _offsets_spec():
    """SMEM spec for the (q_off, k_off) global-position scalars."""
    return pl.BlockSpec((2,), lambda *_: (0,), memory_space=pltpu.SMEM)


def _seg_layouts(q_seg, kv_seg):
    """Lane/sublane-replicated segment-id layouts for the kernels:
    q ids ``(b, sq, NUM_LANES)``, kv ids ``(b, NUM_SUBLANES, sk)``."""
    b, sq = q_seg.shape
    sk = kv_seg.shape[1]
    qs = jax.lax.broadcast_in_dim(
        q_seg.astype(jnp.int32), (b, sq, _NUM_LANES), (0, 1))
    ks = jax.lax.broadcast_in_dim(
        kv_seg.astype(jnp.int32), (b, _NUM_SUBLANES, sk), (0, 2))
    return qs, ks


def _seg_metadata(q_seg, kv_seg, blk_q, blk_k, pad_id=None):
    """Per-block metadata for CONTIGUOUS (non-decreasing) segment ids.

    Returns ``(bounds_q, bounds_k, qmm, kmm)``: ``bounds_q[b, 0/1, i]`` is
    the [start, end) k-block range intersecting q block ``i``'s segment span
    (symmetrically ``bounds_k`` over q blocks), and ``qmm``/``kmm`` are the
    per-block (min, max) segment ids — the full/partial block classifier.
    With ``pad_id`` set, all-padding blocks get EMPTY ranges and ranges
    never extend into the all-padding suffix, so trailing padding costs no
    score blocks at all. Computed with plain XLA reductions OUTSIDE the
    kernel and read from SMEM inside — the Pallas-native replacement for
    the reference kernel's cu_seqlens binary search per CTA (fmha kernel
    launch geometry)."""
    b, sq = q_seg.shape
    sk = kv_seg.shape[1]
    nq, nk = sq // blk_q, sk // blk_k
    qb = q_seg.reshape(b, nq, blk_q)
    kb = kv_seg.reshape(b, nk, blk_k)
    qmin, qmax = qb.min(-1), qb.max(-1)  # (b, nq)
    kmin, kmax = kb.min(-1), kb.max(-1)  # (b, nk)
    # monotone ids: blocks wholly before/after the span count as offsets
    start_q = jnp.sum(kmax[:, None, :] < qmin[:, :, None], axis=-1)
    end_q = nk - jnp.sum(kmin[:, None, :] > qmax[:, :, None], axis=-1)
    start_k = jnp.sum(qmax[:, None, :] < kmin[:, :, None], axis=-1)
    end_k = nq - jnp.sum(qmin[:, None, :] > kmax[:, :, None], axis=-1)
    if pad_id is not None:
        # monotone ids put all-padding blocks (min == pad) in a suffix:
        # give them empty ranges and stop every range at the suffix
        real_k = nk - jnp.sum(kmin == pad_id, axis=-1, keepdims=True)
        end_q = jnp.minimum(end_q, real_k)
        pad_q = qmin == pad_id
        start_q = jnp.where(pad_q, 0, start_q)
        end_q = jnp.where(pad_q, 0, end_q)
        real_q = nq - jnp.sum(qmin == pad_id, axis=-1, keepdims=True)
        end_k = jnp.minimum(end_k, real_q)
        pad_k = kmin == pad_id
        start_k = jnp.where(pad_k, 0, start_k)
        end_k = jnp.where(pad_k, 0, end_k)
    bounds_q = jnp.stack([start_q, end_q], axis=1).astype(jnp.int32)
    bounds_k = jnp.stack([start_k, end_k], axis=1).astype(jnp.int32)
    qmm = jnp.stack([qmin, qmax], axis=1).astype(jnp.int32)  # (b, 2, nq)
    kmm = jnp.stack([kmin, kmax], axis=1).astype(jnp.int32)  # (b, 2, nk)
    return bounds_q, bounds_k, qmm, kmm


def _seg_specs(blk_q, sk, reorder=None):
    """(q-ids, kv-ids) BlockSpecs for grids ordered (b, h, q)."""
    r = reorder if reorder is not None else (lambda f: f)
    return [
        pl.BlockSpec((1, blk_q, _NUM_LANES),
                     r(lambda bi, hi, qi: (bi, qi, 0)),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, _NUM_SUBLANES, sk),
                     r(lambda bi, hi, qi: (bi, 0, 0)),
                     memory_space=pltpu.VMEM),
    ]


def _smem_pair_spec(n, reorder=None):
    """SMEM spec for a (b, 2, n) per-block metadata array (bounds, min/max)."""
    r = reorder if reorder is not None else (lambda f: f)
    return pl.BlockSpec((1, 2, n), r(lambda bi, hi, qi: (bi, 0, 0)),
                        memory_space=pltpu.SMEM)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "causal", "blk_q", "blk_k", "pad_id",
                     "contiguous", "stream", "window"),
)
def _flash_fwd(q, k, v, bias, offsets, q_seg=None, kv_seg=None, *,
               scale, causal, blk_q, blk_k, pad_id=None, contiguous=True,
               stream=False, window=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if stream:
        assert bias is None, "streamed path does not support dense bias"
        return _flash_fwd_stream(q, k, v, offsets, q_seg, kv_seg,
                                 scale=scale, causal=causal, blk_q=blk_q,
                                 blk_k=blk_k, pad_id=pad_id,
                                 contiguous=contiguous, window=window)
    nq = sq // blk_q
    grid = (b, h, nq)
    lse_g = _lse_group(nq)
    qspec = pl.BlockSpec((1, 1, blk_q, d), lambda bi, hi, qi: (bi, hi, qi, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0),
                         memory_space=pltpu.VMEM)
    ospec = qspec
    lspec = pl.BlockSpec((1, 1, lse_g, blk_q),
                         lambda bi, hi, qi: (bi, hi, qi // lse_g, 0),
                         memory_space=pltpu.VMEM)
    in_specs = [qspec, kspec, kspec]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(_bias_spec(bias, blk_q, sk))
        args.append(bias)
    if q_seg is not None:
        qs, ks = _seg_layouts(q_seg, kv_seg)
        bounds_q, _, _, kmm = _seg_metadata(q_seg, kv_seg, blk_q, blk_k,
                                            pad_id)
        in_specs += _seg_specs(blk_q, sk)
        args += [qs, ks]
        in_specs.append(_smem_pair_spec(sk // blk_k))
        args.append(kmm)
        if contiguous:
            in_specs.append(_smem_pair_spec(sq // blk_q))
            args.append(bounds_q)
    if offsets is not None:
        in_specs.append(_offsets_spec())
        args.append(offsets)
    has_bias, has_off = bias is not None, offsets is not None
    has_seg, has_bnd = q_seg is not None, q_seg is not None and contiguous

    def kern(*refs):
        refs = list(refs)
        qr, kr, vr = refs[:3]
        i = 3
        br = refs[i] if has_bias else None
        i += has_bias
        qsr = refs[i] if has_seg else None
        ksr = refs[i + 1] if has_seg else None
        kmmr = refs[i + 2] if has_seg else None
        i += 3 * has_seg
        bndr = refs[i] if has_bnd else None
        i += has_bnd
        offr = refs[i] if has_off else None
        i += has_off
        orf, lr = refs[i], refs[i + 1]
        _fwd_kernel(qr, kr, vr, br, qsr, ksr, kmmr, bndr, offr, orf, lr,
                    scale=scale, causal=causal, blk_q=blk_q, blk_k=blk_k,
                    pad_id=pad_id, window=window, lse_group=lse_g)

    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[ospec, lspec],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, nq, blk_q), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    lse = lse.reshape(b, h, sq, 1)  # dense either way outside the call
    # Named for selective activation checkpointing: a remat policy saving
    # these (e.g. GPTConfig.remat_policy="save_attn") keeps the kernel's
    # output + logsumexp so backward never re-runs the forward kernel —
    # O(b*h*s*d) memory buys back the most expensive recompute in the layer.
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, lse


def _flash_fwd_stream(q, k, v, offsets, q_seg, kv_seg, *, scale, causal,
                      blk_q, blk_k, pad_id, contiguous, window=None):
    """Streamed forward: grid (b, h, nq, nk); K/V arrive blockwise. With a
    ``window`` and static positions (no ring offsets) the k extent shrinks
    to the band's block width via :func:`_window_grid` — O(s·w) trips and
    DMA instead of O(s²)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nq, nk = sq // blk_q, sk // blk_k
    nkw, k_base, kmap = _window_grid_maps(blk_q, blk_k, nk, causal, window,
                                          offsets)
    grid = (b, h, nq, nkw)
    qspec = pl.BlockSpec((1, 1, blk_q, d),
                         lambda bi, hi, qi, kj: (bi, hi, qi, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, 1, blk_k, d),
                         lambda bi, hi, qi, kj: (bi, hi, kmap(qi, kj), 0),
                         memory_space=pltpu.VMEM)
    # lse travels as a DENSE (b, h, nq, blk_q) table — a (b, h, sq, 1)
    # custom-call operand gets the T(8, 128) layout, which lane-pads the
    # size-1 minor dim 128x: at 512k tokens that is a 2 GB HBM buffer for
    # 16 MB of logsumexp (measured; the official TPU flash/splash kernels
    # pay the same via their (..., 128) replication). The table is
    # windowed in _lse_group-row groups (constant VMEM at any sequence
    # length) and each block reads or writes its row with a cheap
    # (1, blk) <-> (blk, 1) transpose.
    lse_g = _lse_group(nq)
    lse_spec = pl.BlockSpec((1, 1, lse_g, blk_q),
                            lambda bi, hi, qi, kj: (bi, hi, qi // lse_g, 0),
                            memory_space=pltpu.VMEM)
    in_specs = [qspec, kspec, kspec]
    args = [q, k, v]
    has_seg = q_seg is not None
    has_bnd = has_seg and contiguous
    if has_seg:
        qs, ks = _seg_layouts(q_seg, kv_seg)
        bounds_q, _, qmm, kmm = _seg_metadata(q_seg, kv_seg, blk_q, blk_k,
                                              pad_id)
        in_specs += [
            pl.BlockSpec((1, blk_q, _NUM_LANES),
                         lambda bi, hi, qi, kj: (bi, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _NUM_SUBLANES, blk_k),
                         lambda bi, hi, qi, kj: (bi, 0, kmap(qi, kj)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2, nk), lambda bi, hi, qi, kj: (bi, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 2, nq), lambda bi, hi, qi, kj: (bi, 0, 0),
                         memory_space=pltpu.SMEM),
        ]
        args += [qs, ks, kmm, qmm]
        if has_bnd:
            in_specs.append(
                pl.BlockSpec((1, 2, nq), lambda bi, hi, qi, kj: (bi, 0, 0),
                             memory_space=pltpu.SMEM))
            args.append(bounds_q)
    has_off = offsets is not None
    if has_off:
        in_specs.append(_offsets_spec())
        args.append(offsets)

    def kern(*refs):
        refs = list(refs)
        qr, kr, vr = refs[:3]
        i = 3
        qsr = refs[i] if has_seg else None
        ksr = refs[i + 1] if has_seg else None
        kmmr = refs[i + 2] if has_seg else None
        qmmr = refs[i + 3] if has_seg else None
        i += 4 * has_seg
        bndr = refs[i] if has_bnd else None
        i += has_bnd
        offr = refs[i] if has_off else None
        i += has_off
        orf, lr = refs[i], refs[i + 1]
        accr, mr, lr2 = refs[i + 2], refs[i + 3], refs[i + 4]
        _fwd_kernel_stream(qr, kr, vr, qsr, ksr, kmmr, qmmr, bndr, offr,
                           orf, lr, accr, mr, lr2, scale=scale,
                           causal=causal, blk_q=blk_q, blk_k=blk_k,
                           pad_id=pad_id, nk=nk, window=window,
                           k_base=k_base, lse_group=lse_g)

    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[qspec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, nq, blk_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, d), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    # external interface stays (b, h, sq, 1) — a plain XLA reshape, dense
    # either way outside the custom call
    lse = lse.reshape(b, h, sq, 1)
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, lse


def _flash_bwd_stream(q, k, v, offsets, o, lse, do, q_seg, kv_seg, *,
                      scale, causal, blk_q, blk_k, pad_id, contiguous,
                      window=None):
    """Streamed backward: dQ over grid (b, h, nq, nk) with K/V blockwise;
    dK/dV over grid (b, h, nk, nq) with Q/dO/lse/delta blockwise. VMEM
    residency is block-bounded — in particular the lane-replicated q-id
    tile arrives per q-block instead of whole-sq (the ADVICE r3 medium)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nq, nk = sq // blk_q, sk // blk_k
    # lse/delta in the dense (b, h, nq, blk_q) table layout (see
    # _flash_fwd_stream) — the (b, h, sq, 1) shape would be lane-padded
    # 128x at the custom-call boundary
    lse = lse.reshape(b, h, nq, blk_q)
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1).reshape(b, h, nq, blk_q)
    has_seg = q_seg is not None
    has_bnd = has_seg and contiguous
    has_off = offsets is not None
    if has_seg:
        qs_l, ks_l = _seg_layouts(q_seg, kv_seg)
        bounds_q, bounds_k, qmm, kmm = _seg_metadata(
            q_seg, kv_seg, blk_q, blk_k, pad_id)
    # window-restricted inner grids (see _flash_fwd_stream / _window_grid)
    nkw, k_base, kmap = _window_grid_maps(blk_q, blk_k, nk, causal, window,
                                          offsets)

    # dQ pass
    qspec = pl.BlockSpec((1, 1, blk_q, d),
                         lambda bi, hi, qi, kj: (bi, hi, qi, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, 1, blk_k, d),
                         lambda bi, hi, qi, kj: (bi, hi, kmap(qi, kj), 0),
                         memory_space=pltpu.VMEM)
    lse_g = _lse_group(nq)
    lblk = pl.BlockSpec((1, 1, lse_g, blk_q),
                        lambda bi, hi, qi, kj: (bi, hi, qi // lse_g, 0),
                        memory_space=pltpu.VMEM)
    in_specs = [qspec, kspec, kspec]
    args = [q, k, v]
    if has_seg:
        in_specs += [
            pl.BlockSpec((1, blk_q, _NUM_LANES),
                         lambda bi, hi, qi, kj: (bi, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _NUM_SUBLANES, blk_k),
                         lambda bi, hi, qi, kj: (bi, 0, kmap(qi, kj)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2, nk), lambda bi, hi, qi, kj: (bi, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 2, nq), lambda bi, hi, qi, kj: (bi, 0, 0),
                         memory_space=pltpu.SMEM),
        ]
        args += [qs_l, ks_l, kmm, qmm]
        if has_bnd:
            in_specs.append(
                pl.BlockSpec((1, 2, nq), lambda bi, hi, qi, kj: (bi, 0, 0),
                             memory_space=pltpu.SMEM))
            args.append(bounds_q)
    if has_off:
        in_specs.append(_offsets_spec())
        args.append(offsets)
    in_specs += [qspec, lblk, lblk]
    args += [do, lse, delta]

    def dq_kern(*refs):
        refs = list(refs)
        qr, kr, vr = refs[:3]
        i = 3
        qsr = refs[i] if has_seg else None
        ksr = refs[i + 1] if has_seg else None
        kmmr = refs[i + 2] if has_seg else None
        qmmr = refs[i + 3] if has_seg else None
        i += 4 * has_seg
        bndr = refs[i] if has_bnd else None
        i += has_bnd
        offr = refs[i] if has_off else None
        i += has_off
        dor, lr, dr, dqr, dq_accr = refs[i:i + 5]
        _bwd_dq_kernel_stream(qr, kr, vr, qsr, ksr, kmmr, qmmr, bndr, offr,
                              dor, lr, dr, dqr, dq_accr, scale=scale,
                              causal=causal, blk_q=blk_q, blk_k=blk_k,
                              pad_id=pad_id, nk=nk, window=window,
                              k_base=k_base, lse_group=lse_g)

    dq = pl.pallas_call(
        dq_kern,
        grid=(b, h, nq, nkw),
        in_specs=in_specs,
        out_specs=[qspec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        scratch_shapes=[pltpu.VMEM((blk_q, d), jnp.float32)],
        interpret=_interpret(),
    )(*args)[0]

    # dK/dV pass
    nqw, q_base, qmap = _window_grid_maps(blk_k, blk_q, nq, causal, window,
                                          offsets, inner_is_k=False)
    qspec2 = pl.BlockSpec((1, 1, blk_q, d),
                          lambda bi, hi, ki, qi: (bi, hi, qmap(ki, qi), 0),
                          memory_space=pltpu.VMEM)
    kspec2 = pl.BlockSpec((1, 1, blk_k, d),
                          lambda bi, hi, ki, qi: (bi, hi, ki, 0),
                          memory_space=pltpu.VMEM)
    lblk2 = pl.BlockSpec((1, 1, lse_g, blk_q),
                         lambda bi, hi, ki, qi: (bi, hi,
                                                 qmap(ki, qi) // lse_g, 0),
                         memory_space=pltpu.VMEM)
    in_specs2 = [qspec2, kspec2, kspec2]
    args2 = [q, k, v]
    if has_seg:
        in_specs2 += [
            pl.BlockSpec((1, blk_q, _NUM_LANES),
                         lambda bi, hi, ki, qi: (bi, qmap(ki, qi), 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _NUM_SUBLANES, blk_k),
                         lambda bi, hi, ki, qi: (bi, 0, ki),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2, nq), lambda bi, hi, ki, qi: (bi, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 2, nk), lambda bi, hi, ki, qi: (bi, 0, 0),
                         memory_space=pltpu.SMEM),
        ]
        args2 += [qs_l, ks_l, qmm, kmm]
        if has_bnd:
            in_specs2.append(
                pl.BlockSpec((1, 2, nk), lambda bi, hi, ki, qi: (bi, 0, 0),
                             memory_space=pltpu.SMEM))
            args2.append(bounds_k)
    if has_off:
        in_specs2.append(_offsets_spec())
        args2.append(offsets)
    in_specs2 += [qspec2, lblk2, lblk2]
    args2 += [do, lse, delta]

    def dkv_kern(*refs):
        refs = list(refs)
        qr, kr, vr = refs[:3]
        i = 3
        qsr = refs[i] if has_seg else None
        ksr = refs[i + 1] if has_seg else None
        qmmr = refs[i + 2] if has_seg else None
        kmmr = refs[i + 3] if has_seg else None
        i += 4 * has_seg
        bndr = refs[i] if has_bnd else None
        i += has_bnd
        offr = refs[i] if has_off else None
        i += has_off
        dor, lr, dr, dkr, dvr, dk_accr, dv_accr = refs[i:i + 7]
        _bwd_dkv_kernel_stream(qr, kr, vr, qsr, ksr, qmmr, kmmr, bndr, offr,
                               dor, lr, dr, dkr, dvr, dk_accr, dv_accr,
                               scale=scale, causal=causal, blk_q=blk_q,
                               blk_k=blk_k, pad_id=pad_id, nq=nq,
                               window=window, q_base=q_base,
                               lse_group=lse_g)

    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(b, h, nk, nqw),
        in_specs=in_specs2,
        out_specs=[kspec2, kspec2],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, d), jnp.float32),
            pltpu.VMEM((blk_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args2)
    return dq, dk, dv, None


@functools.partial(
    jax.jit,
    static_argnames=("scale", "causal", "blk_q", "blk_k", "pad_id",
                     "contiguous", "stream", "window"),
)
def _flash_bwd(q, k, v, bias, offsets, o, lse, do, q_seg=None, kv_seg=None, *,
               scale, causal, blk_q, blk_k, pad_id=None, contiguous=True,
               stream=False, window=None):
    if stream:
        assert bias is None, "streamed path does not support dense bias"
        return _flash_bwd_stream(q, k, v, offsets, o, lse, do, q_seg, kv_seg,
                                 scale=scale, causal=causal, blk_q=blk_q,
                                 blk_k=blk_k, pad_id=pad_id,
                                 contiguous=contiguous, window=window)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nq = sq // blk_q
    lse_g = _lse_group(nq)
    # dense (b, h, nq, blk_q) lse/delta tables (see _flash_fwd_stream)
    lse = lse.reshape(b, h, nq, blk_q)
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1).reshape(b, h, nq, blk_q)
    has_seg = q_seg is not None
    has_bnd = has_seg and contiguous
    if has_seg:
        qs_l, ks_l = _seg_layouts(q_seg, kv_seg)
        bounds_q, bounds_k, qmm, kmm = _seg_metadata(
            q_seg, kv_seg, blk_q, blk_k, pad_id)

    # dQ pass: grid over (b, h, q-blocks), reordered so dbias accumulation
    # over broadcast dims happens on consecutive steps (see _dq_grid_order);
    # also emits dS accumulated into dbias.
    b_bcast = bias is not None and bias.shape[0] == 1
    h_bcast = bias is not None and bias.shape[1] == 1
    order = _dq_grid_order(bias, b_bcast, h_bcast)
    dims = {name: pos for pos, name in enumerate(order)}
    sizes = {"b": b, "h": h, "q": sq // blk_q}
    grid = tuple(sizes[name] for name in order)

    def reorder(fn):
        """Wrap a logical (bi, hi, qi) index map for the reordered grid."""

        def idx(*a):
            return fn(a[dims["b"]], a[dims["h"]], a[dims["q"]])

        return idx

    qspec = pl.BlockSpec((1, 1, blk_q, d), reorder(lambda bi, hi, qi: (bi, hi, qi, 0)),
                         memory_space=pltpu.VMEM)
    kfull = pl.BlockSpec((1, 1, sk, d), reorder(lambda bi, hi, qi: (bi, hi, 0, 0)),
                         memory_space=pltpu.VMEM)
    lblk = pl.BlockSpec((1, 1, lse_g, blk_q),
                        reorder(lambda bi, hi, qi: (bi, hi, qi // lse_g, 0)),
                        memory_space=pltpu.VMEM)

    in_specs = [qspec, kfull, kfull]
    args = [q, k, v]
    if bias is not None:
        bb, bh = bias.shape[0], bias.shape[1]
        in_specs.append(pl.BlockSpec(
            (1, 1, blk_q, sk),
            reorder(lambda bi, hi, qi: (bi if bb > 1 else 0, hi if bh > 1 else 0, qi, 0)),
            memory_space=pltpu.VMEM,
        ))
        args.append(bias)
    if has_seg:
        in_specs += _seg_specs(blk_q, sk, reorder=reorder)
        args += [qs_l, ks_l]
        in_specs.append(_smem_pair_spec(sk // blk_k, reorder=reorder))
        args.append(kmm)
        if has_bnd:
            in_specs.append(_smem_pair_spec(sq // blk_q, reorder=reorder))
            args.append(bounds_q)
    if offsets is not None:
        in_specs.append(_offsets_spec())
        args.append(offsets)
    in_specs += [qspec, lblk, lblk]
    args += [do, lse, delta]
    has_bias, has_off = bias is not None, offsets is not None

    def dq_kern(*refs):
        refs = list(refs)
        qr, kr, vr = refs[:3]
        i = 3
        br = refs[i] if has_bias else None
        i += has_bias
        qsr = refs[i] if has_seg else None
        ksr = refs[i + 1] if has_seg else None
        kmmr = refs[i + 2] if has_seg else None
        i += 3 * has_seg
        bndr = refs[i] if has_bnd else None
        i += has_bnd
        offr = refs[i] if has_off else None
        i += has_off
        dor, lr, dr, dqr = refs[i:i + 4]
        dbr = refs[i + 4] if has_bias else None
        _bwd_dq_kernel(qr, kr, vr, br, qsr, ksr, kmmr, bndr, offr, dor, lr,
                       dr, dqr, dbr,
                       scale=scale, causal=causal, blk_q=blk_q, blk_k=blk_k,
                       pad_id=pad_id, b_bcast=b_bcast, h_bcast=h_bcast,
                       dims=dims, window=window, lse_group=lse_g)

    out_specs = [qspec]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    if bias is not None:
        out_specs.append(pl.BlockSpec(
            (1, 1, blk_q, sk),
            reorder(lambda bi, hi, qi: (bi if bb > 1 else 0, hi if bh > 1 else 0, qi, 0)),
            memory_space=pltpu.VMEM,
        ))
        out_shape.append(jax.ShapeDtypeStruct(bias.shape, jnp.float32))
    res = pl.pallas_call(
        dq_kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(*args)
    dq, dbias = (res[0], res[1]) if bias is not None else (res[0], None)

    # dK/dV pass: grid over k blocks; q/do/lse/delta stream in full.
    qfull = pl.BlockSpec((1, 1, sq, d), lambda bi, hi, ki: (bi, hi, 0, 0),
                         memory_space=pltpu.VMEM)
    kblk = pl.BlockSpec((1, 1, blk_k, d), lambda bi, hi, ki: (bi, hi, ki, 0),
                        memory_space=pltpu.VMEM)
    lfull = pl.BlockSpec((1, 1, nq, blk_q), lambda bi, hi, ki: (bi, hi, 0, 0),
                         memory_space=pltpu.VMEM)
    in_specs2 = [qfull, kblk, kblk]
    args2 = [q, k, v]
    if bias is not None:
        bb, bh = bias.shape[0], bias.shape[1]
        bspec2 = pl.BlockSpec(
            (1, 1, sq, blk_k),
            lambda bi, hi, ki: (bi if bb > 1 else 0, hi if bh > 1 else 0, 0, ki),
            memory_space=pltpu.VMEM,
        )
        in_specs2.append(bspec2)
        args2.append(bias)
    if has_seg:
        # this pass streams q: q ids arrive FULL, bounds indexed by k block
        in_specs2 += [
            pl.BlockSpec((1, sq, _NUM_LANES),
                         lambda bi, hi, ki: (bi, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _NUM_SUBLANES, sk),
                         lambda bi, hi, ki: (bi, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2, sq // blk_q), lambda bi, hi, ki: (bi, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 2, sk // blk_k), lambda bi, hi, ki: (bi, 0, 0),
                         memory_space=pltpu.SMEM),
        ]
        args2 += [qs_l, ks_l, qmm, kmm]
        if has_bnd:
            in_specs2.append(pl.BlockSpec(
                (1, 2, sk // blk_k), lambda bi, hi, ki: (bi, 0, 0),
                memory_space=pltpu.SMEM))
            args2.append(bounds_k)
    if offsets is not None:
        in_specs2.append(_offsets_spec())
        args2.append(offsets)
    in_specs2 += [qfull, lfull, lfull]
    args2 += [do, lse, delta]

    def dkv_kern(*refs):
        refs = list(refs)
        qr, kr, vr = refs[:3]
        i = 3
        br = refs[i] if has_bias else None
        i += has_bias
        qsr = refs[i] if has_seg else None
        ksr = refs[i + 1] if has_seg else None
        qmmr = refs[i + 2] if has_seg else None
        kmmr = refs[i + 3] if has_seg else None
        i += 4 * has_seg
        bndr = refs[i] if has_bnd else None
        i += has_bnd
        offr = refs[i] if has_off else None
        i += has_off
        dor, lr, dr, dkr, dvr = refs[i:i + 5]
        _bwd_dkv_kernel(qr, kr, vr, br, qsr, ksr, qmmr, kmmr, bndr, offr,
                        dor, lr, dr, dkr, dvr,
                        scale=scale, causal=causal, blk_q=blk_q, blk_k=blk_k,
                        pad_id=pad_id, window=window)

    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(b, h, sk // blk_k),
        in_specs=in_specs2,
        out_specs=[kblk, kblk],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=_interpret(),
    )(*args2)
    return dq, dk, dv, dbias


# ---------------------------------------------------------------------------
# custom_vjp + public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(6, 7, 8, 9, 10, 11, 12, 13))
def _flash(q, k, v, bias, q_seg, kv_seg, scale, causal, blk_q, blk_k,
           pad_id, contiguous, stream, window):
    o, _ = _flash_fwd(q, k, v, bias, None, q_seg, kv_seg,
                      scale=scale, causal=causal, blk_q=blk_q, blk_k=blk_k,
                      pad_id=pad_id, contiguous=contiguous, stream=stream,
                      window=window)
    return o


def _flash_vjp_fwd(q, k, v, bias, q_seg, kv_seg, scale, causal, blk_q, blk_k,
                   pad_id, contiguous, stream, window):
    o, lse = _flash_fwd(q, k, v, bias, None, q_seg, kv_seg,
                        scale=scale, causal=causal, blk_q=blk_q, blk_k=blk_k,
                        pad_id=pad_id, contiguous=contiguous, stream=stream,
                        window=window)
    return o, (q, k, v, bias, q_seg, kv_seg, o, lse)


def _flash_vjp_bwd(scale, causal, blk_q, blk_k, pad_id, contiguous, stream,
                   window, res, do):
    q, k, v, bias, q_seg, kv_seg, o, lse = res
    dq, dk, dv, dbias = _flash_bwd(q, k, v, bias, None, o, lse, do,
                                   q_seg, kv_seg, scale=scale,
                                   causal=causal, blk_q=blk_q, blk_k=blk_k,
                                   pad_id=pad_id, contiguous=contiguous,
                                   stream=stream, window=window)
    if dbias is not None:
        dbias = dbias.astype(bias.dtype)
    # segment ids are integer inputs: symbolically-zero cotangents
    return dq, dk, dv, dbias, None, None


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# The resident layout's worst-case per-program VMEM residency (bytes); when
# it exceeds this budget the streamed kernels take over. ~16 MB VMEM minus
# headroom for double buffering, accumulators, and Mosaic temporaries.
_RESIDENT_VMEM_BUDGET = 6 * 1024 * 1024

# one-time hint that a packed (non-decreasing) segment layout was passed
# without opting into block skipping (ADVICE r4 low #4)
_WARNED_PACKED_OPT_IN = False


def _resident_vmem_bytes(sq, sk, d, blk_q, blk_k, itemsize, has_bias,
                         has_seg):
    """Dominant per-program VMEM residency of the resident layout, for the
    fwd/dQ passes (whole K+V) and the dK/dV pass (whole Q/dO + the
    lane-replicated q-id tile — the ADVICE r3 medium: residency scales
    with TOTAL tokens, not max_seqlen, on the packed path).

    VMEM tiles pad the MINOR dim to the 128-lane vreg width: a head_dim
    of 32 occupies 128 lanes — observed live: a d=32, s=8192 resident
    dK/dV pass allocates 17.3 MB where the unpadded arithmetic says
    1.6 MB. The estimate must count PADDED bytes or 'auto' keeps
    resident layouts that cannot compile. (lse/delta now travel as dense
    (nq, blk_q) tables — sq·4 bytes each, no lane padding — so they no
    longer dominate; the q/do/K/V operand padding does.)"""
    d_eff = -(-d // _NUM_LANES) * _NUM_LANES
    seg_fwd = (blk_q * _NUM_LANES + _NUM_SUBLANES * sk) * 4 if has_seg else 0
    fwd = (2 * sk * d_eff * itemsize
           + (blk_q * sk * 4 if has_bias else 0) + seg_fwd)
    seg_dkv = (sq * _NUM_LANES + _NUM_SUBLANES * sk) * 4 if has_seg else 0
    dkv = (3 * sq * d_eff * itemsize  # q, do (+ dq-pass K/V ≈ fwd term)
           + 2 * sq * 4  # lse + delta dense tables
           + (sq * blk_k * 4 if has_bias else 0) + seg_dkv)
    return max(fwd, dkv)


# ---------------------------------------------------------------------------
# lint/analyzer introspection hooks (apex_tpu.lint.trace lane-padding
# auditor; monitor/hbm.py documents the same tiling for HBM): the lane and
# sublane constants the 'auto' layout decision compiles by, and the
# resident-layout residency estimator, public so analyzers estimate with
# the exact rules this kernel is calibrated against.
# ---------------------------------------------------------------------------

NUM_LANES = _NUM_LANES
NUM_SUBLANES = _NUM_SUBLANES
resident_vmem_bytes = _resident_vmem_bytes


# Measurement basis of the stream='auto' throughput crossover: d=64 bf16
# on-chip fwd+bwd. The re-streamed q/do rows move LANE-PADDED bytes
# (minor dim pads to the 128-lane vreg width, same rule as
# _resident_vmem_bytes), so the basis row is 128 lanes x 2 B = 256 B.
_CROSSOVER_SEQ = 4096
_CROSSOVER_ROW_BYTES = _NUM_LANES * 2


def _auto_stream(sq, sk, d, blk_q, blk_k, itemsize, has_bias, has_seg):
    """The stream='auto' decision, shared with ``ring_attention``:
    ``(vmem_wall, crossover)``.

    ``vmem_wall``: the resident layout's estimated residency exceeds the
    VMEM budget — it cannot compile, streaming is mandatory.
    ``crossover``: a measured THROUGHPUT boundary, not a memory wall: the
    resident dK/dV pass re-streams whole-sq q/do per k block (O(nk·sq·d)
    DMA) and falls behind the streamed layout past ~2k — on-chip fwd+bwd
    AT d=64 bf16: s=2048 resident 12.2 vs streamed 13.4 ms, s=4096
    resident 27.4 vs streamed 17.7 ms. (The dense lse tables made
    4096-resident COMPILE, so the wall check alone would pick the slower
    layout.) That re-streamed traffic moves PADDED rows — the minor dim
    pads to 128 lanes, so every d <= 128 DMAs the same
    ``128 * itemsize`` bytes/row and the measured 4096 boundary stands
    across the whole d=32..128 bf16 family (a naive ``d * itemsize``
    scaling would halve it for d=128 where the physical traffic is
    unchanged). The boundary moves DOWN only when the padded row grows:
    fp32 doubles it (any d <= 128 -> 2048), as does d > 128. The d=64
    bf16 measurement is the only calibrated point; other (d, itemsize)
    boundaries are this traffic-proportional extrapolation."""
    wall = _resident_vmem_bytes(sq, sk, d, blk_q, blk_k, itemsize,
                                has_bias, has_seg) > _RESIDENT_VMEM_BUDGET
    row_bytes = (-(-d // _NUM_LANES) * _NUM_LANES) * itemsize
    crossover_seq = min(_CROSSOVER_SEQ,
                        _CROSSOVER_SEQ * _CROSSOVER_ROW_BYTES
                        // max(row_bytes, 1))
    return wall, max(sq, sk) >= crossover_seq


def mha_reference(
    q: jax.Array, k: jax.Array, v: jax.Array,
    bias: Optional[jax.Array] = None,
    *, causal: bool = False, scale: Optional[float] = None,
    segment_ids: Optional[Tuple[jax.Array, jax.Array]] = None,
    pad_id: Optional[int] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Unfused XLA attention (the torch-softmax fallback path,
    fused_softmax.py:193-199 forward_torch_softmax equivalent)."""
    d = q.shape[-1]
    scale = (d ** -0.5) if scale is None else scale
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    # a cross-shape (sq != sk) window can fully mask rows too (queries
    # past sk + window), so they need the same exact-zero treatment as
    # segment-masked rows
    masked = segment_ids is not None or window is not None
    if segment_ids is not None:
        q_seg, kv_seg = segment_ids
        valid = q_seg[:, None, :, None] == kv_seg[:, None, None, :]
        if pad_id is not None:
            valid = valid & (kv_seg != pad_id)[:, None, None, :]
        s = jnp.where(valid, s, _NEG_INF)
    if causal or window is not None:
        sq, sk = s.shape[-2], s.shape[-1]
        s = _dense_pos_masks(s, jnp.arange(sq)[:, None],
                             jnp.arange(sk)[None, :], causal, window)
    p = jax.nn.softmax(s, axis=-1)
    if masked:
        # match the kernel: rows with no visible key output exactly zero
        # (softmax of an all -inf row would be uniform, not zero). Derived
        # AFTER all masks: a row whose same-segment keys all sit above the
        # causal diagonal is fully masked too (ADVICE r3 low #2 — deciding
        # from the segment mask alone diverged from the kernel there).
        fully_masked = jnp.max(s, axis=-1, keepdims=True) <= _NEG_INF / 2
        p = jnp.where(fully_masked, 0.0, p)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    segment_ids: Optional[Tuple[jax.Array, jax.Array]] = None,
    pad_id: Optional[int] = None,
    contiguous_segments: bool = False,
    causal: bool = False,
    scale: Optional[float] = None,
    window: Optional[int] = None,
    block_q: int = 1024,
    block_k: int = 1024,
    impl: str = "auto",
    stream: str = "auto",
) -> jax.Array:
    """Fused multi-head attention.

    Args:
      q, k, v: ``(batch, heads, seq, head_dim)``; kv seq may differ from q seq
        (encoder-decoder attention, apex/contrib/multihead_attn encdec path).
      bias: optional additive bias broadcastable to ``(b, h, sq, sk)``
        (additive-mask attention; use -10000 for masked positions like the
        reference's masked_fill).
      segment_ids: optional ``(q_seg, kv_seg)`` int arrays of shape
        ``(b, sq)`` / ``(b, sk)``: a query attends only keys with an EQUAL
        segment id — packed-varlen attention (the reference fmha's
        cu_seqlens semantics, apex/contrib/fmha/fmha.py:33-74). Rows whose
        every key is masked output exactly 0.
      pad_id: segment id marking padding: such keys are never attended
        (and padded query rows output 0).
      contiguous_segments: ids are non-decreasing along the sequence (the
        packed layout). Enables block skipping: k blocks whose segment
        range cannot intersect the q block's are never computed, so cost
        scales with ``sum(len_i^2)`` instead of ``total^2``. Default False
        (mask-only): with NON-monotone ids skipping silently drops valid
        q/k pairs, and under ``jit`` (traced ids — the common training
        case) the monotonicity check below cannot run, so opting in is the
        caller asserting the packed layout (``contrib.fmha`` does; ADVICE
        r3 low #3).
      causal: upper-triangular masking (scaled_upper_triang_masked_softmax).
      scale: score scale; defaults to 1/sqrt(head_dim).
      window: sliding-window (local) attention — each query attends only
        the ``window`` most recent positions ``[p-window+1, p]`` when
        causal (the Mistral/Longformer convention) or the symmetric band
        ``[p-window+1, p+window-1]`` when not. Blocks wholly outside the
        band are skipped, so score cost is O(s·window) instead of O(s²).
        Beyond-reference capability: the reference's fmha kernels have
        no local-attention mode; this is the standard long-context
        pairing for the streamed kernels. Composes with ``causal``,
        ``segment_ids``, ``bias``, and streaming.
      impl: 'auto' | 'pallas' | 'xla'.
      stream: 'auto' | 'never' | 'always' — streamed kernels move the
        K/V loop into the Pallas grid so VMEM residency is block-bounded
        rather than sequence-bounded. 'auto' switches over when the
        resident layout's estimated residency exceeds the VMEM budget
        (long sequences / large packed token counts). The streamed path
        does not take a dense ``bias`` ('auto' then stays resident).
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = (d ** -0.5) if scale is None else float(scale)
    if window is not None:
        window = int(window)
        if window < 1:
            raise ValueError(f"window must be a positive int, got {window}")
        if window >= max(sq, sk):
            window = None  # the band covers everything: dense attention
    use = _resolve_impl(impl)
    if use == "pallas" and not _supported(sq, sk, d):
        use = "xla"
    global _WARNED_PACKED_OPT_IN
    blk_q = _pick_block(sq, block_q)
    blk_k = _pick_block(sk, block_k)
    if segment_ids is not None:
        q_seg, kv_seg = segment_ids
        if q_seg.shape != (b, sq) or kv_seg.shape != (b, sk):
            raise ValueError(
                f"segment_ids shapes {q_seg.shape}/{kv_seg.shape} do not "
                f"match (batch, seq) = ({b}, {sq})/({b}, {sk})")
        if (contiguous_segments or not _WARNED_PACKED_OPT_IN) and not any(
                isinstance(s, jax.core.Tracer) for s in (q_seg, kv_seg)):
            # once the one-time hint has fired, mask-only callers skip the
            # scan entirely — np.asarray on concrete device arrays is a
            # host fetch per call (a tunnel round-trip through axon)
            # block skipping is only sound for non-decreasing ids; with
            # concrete ids enforce it here (traced ids: the caller owns the
            # guarantee, like the reference's static bucket dispatch)
            import numpy as _np

            monotone = True
            for name, ids in (("q", q_seg), ("kv", kv_seg)):
                a = _np.asarray(ids)
                if (_np.diff(a, axis=-1) < 0).any():
                    monotone = False
                    if contiguous_segments:
                        raise ValueError(
                            f"{name} segment ids are not non-decreasing; "
                            "pass contiguous_segments=False for non-packed "
                            "layouts (mask-only, no block skipping)")
            if monotone and not contiguous_segments:
                # packed layout detected but block skipping left off: the
                # default is the safe mask-only path, which computes
                # total^2 score blocks instead of sum(len_i^2) — tell the
                # caller once so genuinely packed layouts learn to opt in
                if not _WARNED_PACKED_OPT_IN:
                    _WARNED_PACKED_OPT_IN = True
                    import warnings

                    warnings.warn(
                        "flash_attention: segment ids are non-decreasing "
                        "(packed layout) but contiguous_segments=False; "
                        "pass contiguous_segments=True to enable block "
                        "skipping (cost sum(len_i^2) instead of total^2)",
                        stacklevel=2)
        # the lane-replicated kernel layout needs 128-aligned k blocks
        blk_k = _pick_block(sk, block_k, mult=_NUM_LANES)
        if blk_k % _NUM_LANES or sk % blk_k:
            use = "xla"
    if stream not in ("auto", "never", "always"):
        raise ValueError(f"stream must be auto|never|always, got {stream!r}")
    if use == "xla":
        # explicit impl="xla" (or an unsupported-shape fallback): the dense
        # path supports bias and ignores streaming, so return before the
        # stream-vs-bias checks (ADVICE r4: stream="always" + bias must not
        # reject an explicitly requested, working XLA path)
        return mha_reference(q, k, v, bias, causal=causal, scale=scale,
                             segment_ids=segment_ids, pad_id=pad_id,
                             window=window)
    vmem_wall, crossover = _auto_stream(
        sq, sk, d, blk_q, blk_k, q.dtype.itemsize, bias is not None,
        segment_ids is not None)
    do_stream = stream == "always" or (
        stream == "auto" and (vmem_wall or crossover))
    if do_stream and bias is not None:
        if stream == "always":
            raise ValueError("stream='always' does not support dense bias; "
                             "use segment_ids/causal for long sequences")
        # auto: the streamed path lacks the dbias pass. If the RESIDENT
        # layout cannot fit VMEM, proceeding into it would die with an
        # opaque Mosaic allocation failure — take the XLA path
        # (functional, HBM-bound) instead. A throughput-crossover-only
        # trigger keeps the resident kernel: it compiles and beats dense
        # XLA attention even past the crossover.
        do_stream = False
        if vmem_wall:
            use = "xla"
    if use == "xla":
        return mha_reference(q, k, v, bias, causal=causal, scale=scale,
                             segment_ids=segment_ids, pad_id=pad_id,
                             window=window)
    if bias is not None:
        if bias.ndim != 4:
            raise ValueError(f"bias must be rank-4 broadcastable, got shape {bias.shape}")
        # Canonicalize size-1 sq/sk dims away (the kernels tile dims 2/3 at
        # full size). This sits outside the custom_vjp, so AD of broadcast_to
        # sums dbias back to the caller's original shape.
        bb, bh = bias.shape[0], bias.shape[1]
        if bb not in (1, b) or bh not in (1, h):
            raise ValueError(f"bias shape {bias.shape} not broadcastable to "
                             f"({b}, {h}, {sq}, {sk})")
        bias = jnp.broadcast_to(bias, (bb, bh, sq, sk))
    q_seg, kv_seg = segment_ids if segment_ids is not None else (None, None)
    return _flash(q, k, v, bias, q_seg, kv_seg, scale, bool(causal),
                  blk_q, blk_k,
                  None if pad_id is None else int(pad_id),
                  bool(contiguous_segments), do_stream, window)
