"""Fused LayerNorm / RMSNorm — Pallas TPU kernels with custom VJP.

Reference: csrc/layer_norm_cuda_kernel.cu (1 170 LoC of warp-shuffle
reductions) behind apex/normalization/fused_layer_norm.py. The CUDA kernel's
job — one HBM pass for stats+normalize in forward, one fused pass for
dx/dγ/dβ in backward — maps to a Pallas kernel blocked over rows with the
whole hidden dimension resident in VMEM (the reference's fast_layer_norm
supports hidden ≤ 65536, apex/contrib/layer_norm/layer_norm.py:8-53; a
65536-wide fp32 row is 256 KB, comfortably inside ~16 MB VMEM).

Semantics preserved:

- affine / non-affine / bias-free variants (layer_norm_cuda.cpp:428-441);
- mixed dtype: bf16/fp16 activations with fp32 γ/β ("MixedFused",
  fused_layer_norm.py:398-436) — stats and math are always fp32;
- RMSNorm shares the kernel with the mean term dropped
  (manual_rms_norm reference, fused_layer_norm.py:16-29).

``impl='xla'`` provides the lax fallback (the reference falls back to
``F.layer_norm`` when its extension is missing, fused_layer_norm.py:204-219);
``impl='auto'`` picks Pallas on TPU. Interpret mode keeps the Pallas path
testable on CPU.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _interpret() -> bool:
    return not _on_tpu()


def _resolve_impl(impl: str) -> str:
    if impl == "auto":
        return "pallas" if _on_tpu() else "xla"
    if impl not in ("pallas", "xla"):
        raise ValueError(f"impl must be 'auto' | 'pallas' | 'xla', got {impl!r}")
    return impl


def _row_block(n_rows: int, hidden: int) -> int:
    """Rows per grid step: target ~1 MB of fp32 activations per block,
    8-row aligned (fp32 sublane tile)."""
    target = max(1, (1 << 20) // max(1, hidden * 4))
    blk = max(8, min(1024, (target // 8) * 8))
    return min(blk, max(8, ((n_rows + 7) // 8) * 8))


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------


def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps, rms):
    x = x_ref[...].astype(jnp.float32)
    if rms:
        mu = jnp.zeros((x.shape[0], 1), jnp.float32)
        var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    else:
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mu) * rstd
    y = xhat
    if w_ref is not None:
        y = y * w_ref[...].astype(jnp.float32)
    if b_ref is not None:
        y = y + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[...] = mu
    rstd_ref[...] = rstd


def _ln_bwd_kernel(
    g_ref, x_ref, mean_ref, rstd_ref, w_ref, dx_ref, dw_ref, db_ref, *, rms
):
    g = g_ref[...].astype(jnp.float32)
    x = x_ref[...].astype(jnp.float32)
    mu = mean_ref[...]
    rstd = rstd_ref[...]
    xhat = (x - mu) * rstd
    wg = g if w_ref is None else g * w_ref[...].astype(jnp.float32)
    c1 = jnp.mean(wg * xhat, axis=-1, keepdims=True)
    if rms:
        dx = rstd * (wg - xhat * c1)
    else:
        c2 = jnp.mean(wg, axis=-1, keepdims=True)
        dx = rstd * (wg - c2 - xhat * c1)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    # Per-block partial γ/β grads (summed over the row axis outside the
    # kernel) — the two-pass part reduction of layer_norm_cuda_kernel.cu's
    # cuComputePartGradGammaBeta.
    if dw_ref is not None:
        dw_ref[...] = jnp.sum(g * xhat, axis=0).reshape(dw_ref.shape)
    if db_ref is not None:
        db_ref[...] = jnp.sum(g, axis=0).reshape(db_ref.shape)


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


def _pad_rows(x2d, blk):
    rows = x2d.shape[0]
    pad = (-rows) % blk
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    return x2d, rows


@functools.partial(jax.jit, static_argnames=("eps", "rms", "has_w", "has_b"))
def _fwd_pallas(x2d, w, b, *, eps, rms, has_w, has_b):
    rows, hidden = x2d.shape
    blk = _row_block(rows, hidden)
    x2d, true_rows = _pad_rows(x2d, blk)
    grid = x2d.shape[0] // blk

    row_spec = pl.BlockSpec((blk, hidden), lambda i: (i, 0), memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((blk, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((hidden,), lambda i: (0,), memory_space=pltpu.VMEM)

    in_specs = [row_spec]
    args = [x2d]
    if has_w:
        in_specs.append(vec_spec)
        args.append(w)
    if has_b:
        in_specs.append(vec_spec)
        args.append(b)

    def kernel(*refs):
        idx = 1
        w_ref = refs[idx] if has_w else None
        idx += has_w
        b_ref = refs[idx] if has_b else None
        idx += has_b
        _ln_fwd_kernel(
            refs[0], w_ref, b_ref, refs[idx], refs[idx + 1], refs[idx + 2],
            eps=eps, rms=rms,
        )

    y, mean, rstd = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=[row_spec, stat_spec, stat_spec],
        out_shape=[
            jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
            jax.ShapeDtypeStruct((x2d.shape[0], 1), jnp.float32),
            jax.ShapeDtypeStruct((x2d.shape[0], 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return y[:true_rows], mean[:true_rows], rstd[:true_rows]


@functools.partial(jax.jit, static_argnames=("rms", "has_w", "has_b"))
def _bwd_pallas(g2d, x2d, mean, rstd, w, *, rms, has_w, has_b):
    rows, hidden = x2d.shape
    blk = _row_block(rows, hidden)
    g2d, true_rows = _pad_rows(g2d, blk)
    x2d, _ = _pad_rows(x2d, blk)
    mean, _ = _pad_rows(mean, blk)
    rstd, _ = _pad_rows(rstd, blk)
    grid = x2d.shape[0] // blk

    row_spec = pl.BlockSpec((blk, hidden), lambda i: (i, 0), memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((blk, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((hidden,), lambda i: (0,), memory_space=pltpu.VMEM)
    # Per-grid-step partial γ/β sums. Mosaic requires a block's trailing two
    # dims to be 8/128-divisible or equal to the array's; a (1, hidden) block
    # over (grid, hidden) violates the sublane rule, so the partials are
    # (grid, 1, hidden) with the grid axis leading and the block covering the
    # trailing (1, hidden) exactly.
    part_spec = pl.BlockSpec(
        (1, 1, hidden), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )

    in_specs = [row_spec, row_spec, stat_spec, stat_spec]
    args = [g2d, x2d, mean, rstd]
    if has_w:
        in_specs.append(vec_spec)
        args.append(w)

    out_specs = [row_spec]
    out_shape = [jax.ShapeDtypeStruct(x2d.shape, x2d.dtype)]
    if has_w:
        out_specs.append(part_spec)
        out_shape.append(jax.ShapeDtypeStruct((grid, 1, hidden), jnp.float32))
    if has_b:
        out_specs.append(part_spec)
        out_shape.append(jax.ShapeDtypeStruct((grid, 1, hidden), jnp.float32))

    def kernel(*refs):
        w_ref = refs[4] if has_w else None
        outs = refs[4 + has_w :]
        dw_ref = outs[1] if has_w else None
        db_ref = outs[1 + has_w] if has_b else None
        _ln_bwd_kernel(
            refs[0], refs[1], refs[2], refs[3], w_ref, outs[0], dw_ref, db_ref,
            rms=rms,
        )

    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(*args)
    dx = outs[0][:true_rows]
    i = 1
    dw = db = None
    if has_w:
        dw = jnp.sum(outs[i], axis=(0, 1))
        i += 1
    if has_b:
        db = jnp.sum(outs[i], axis=(0, 1))
    return dx, dw, db


# ---------------------------------------------------------------------------
# XLA reference path (fallback and ground truth for tests)
# ---------------------------------------------------------------------------


def _norm_xla(x, w, b, eps, rms):
    x32 = x.astype(jnp.float32)
    if rms:
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        xhat = x32 * jax.lax.rsqrt(var + eps)
    else:
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
        xhat = (x32 - mu) * jax.lax.rsqrt(var + eps)
    y = xhat
    if w is not None:
        y = y * w.astype(jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# Public functional API with custom VJP
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_norm(x, w, b, eps, rms):
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    y, _, _ = _fwd_pallas(
        x2d, w, b, eps=eps, rms=rms, has_w=w is not None, has_b=b is not None
    )
    return y.reshape(shape)


def _fused_norm_fwd(x, w, b, eps, rms):
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    y, mean, rstd = _fwd_pallas(
        x2d, w, b, eps=eps, rms=rms, has_w=w is not None, has_b=b is not None
    )
    return y.reshape(shape), (x2d, mean, rstd, w, b is not None, shape)


def _fused_norm_bwd(eps, rms, res, gy):
    x2d, mean, rstd, w, has_b, shape = res
    g2d = gy.reshape(-1, shape[-1])
    dx, dw, db = _bwd_pallas(
        g2d, x2d, mean, rstd, w, rms=rms, has_w=w is not None, has_b=has_b
    )
    dx = dx.reshape(shape)
    dw = None if w is None else dw.astype(w.dtype)
    db_out = db.astype(w.dtype if w is not None else jnp.float32) if has_b else None
    return dx, dw, db_out


_fused_norm.defvjp(_fused_norm_fwd, _fused_norm_bwd)


def layer_norm(
    x: jax.Array,
    weight: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    eps: float = 1e-5,
    *,
    impl: str = "auto",
) -> jax.Array:
    """Fused LayerNorm over the last dimension.

    The functional form of the reference's ``fused_layer_norm(_affine)``
    (apex/normalization/fused_layer_norm.py:168-202). Stats are fp32
    regardless of input dtype; γ/β may be fp32 with bf16 inputs (the
    MixedFused contract).

    ``impl``: 'pallas' forces the kernel (interpret mode off-TPU), 'xla' the
    lax composition, 'auto' picks pallas on TPU and xla elsewhere."""
    if _resolve_impl(impl) == "xla":
        return _norm_xla(x, weight, bias, eps, rms=False)
    return _fused_norm(x, weight, bias, eps, False)


def rms_norm(
    x: jax.Array,
    weight: Optional[jax.Array] = None,
    eps: float = 1e-5,
    *,
    impl: str = "auto",
) -> jax.Array:
    """Fused RMSNorm (apex/normalization/fused_layer_norm.py:300-396)."""
    if _resolve_impl(impl) == "xla":
        return _norm_xla(x, weight, None, eps, rms=True)
    return _fused_norm(x, weight, None, eps, True)


def layer_norm_reference(x, weight=None, bias=None, eps=1e-5):
    """Pure-XLA ground truth for equivalence tests (the reference tests
    compare against torch.nn.functional.layer_norm, SURVEY.md §4)."""
    return _norm_xla(x, weight, bias, eps, rms=False)


def rms_norm_reference(x, weight=None, eps=1e-5):
    return _norm_xla(x, weight, None, eps, rms=True)
