"""Fused softmax-cross-entropy with label smoothing — Pallas TPU kernel.

Reference: apex/contrib/csrc/xentropy/ behind
apex/contrib/xentropy/softmax_xentropy.py:4-28. The fusion win the CUDA
kernel buys — never materializing the (rows, vocab) probability matrix, and
saving only logits + logsumexp for backward — is the same on TPU: forward is
one VMEM pass producing per-row loss and LSE; backward rebuilds
``softmax - target`` on the fly.

Loss per row (label smoothing ε, vocab K):
``(1-ε)·(lse - x_y) + ε·(lse - mean(x))``; backward
``dx = softmax(x) - (1-ε)·onehot(y) - ε/K``, zeroed for ignored rows.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.layer_norm import _interpret, _resolve_impl, _row_block


def _xent_fwd_kernel(x_ref, y_ref, loss_ref, lse_ref, *, smoothing, ignore_index):
    x = x_ref[...].astype(jnp.float32)  # (blk, vocab)
    labels = y_ref[...]  # (blk, 1) int32
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True)) + m
    vocab = x.shape[-1]
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    x_y = jnp.sum(jnp.where(cols == labels, x, 0.0), axis=-1, keepdims=True)
    nll = lse - x_y
    if smoothing > 0.0:
        smooth = lse - jnp.mean(x, axis=-1, keepdims=True)
        loss = (1.0 - smoothing) * nll + smoothing * smooth
    else:
        loss = nll
    valid = labels != ignore_index
    loss_ref[...] = jnp.where(valid, loss, 0.0)
    lse_ref[...] = lse


def _xent_bwd_kernel(g_ref, x_ref, y_ref, lse_ref, dx_ref, *, smoothing, ignore_index):
    g = g_ref[...]  # (blk, 1)
    x = x_ref[...].astype(jnp.float32)
    labels = y_ref[...]
    lse = lse_ref[...]
    probs = jnp.exp(x - lse)
    vocab = x.shape[-1]
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (cols == labels).astype(jnp.float32)
    dx = probs - (1.0 - smoothing) * onehot - smoothing / vocab
    valid = (labels != ignore_index).astype(jnp.float32)
    dx_ref[...] = (dx * g * valid).astype(dx_ref.dtype)


@functools.partial(jax.jit, static_argnames=("smoothing", "ignore_index"))
def _fwd(logits, labels, *, smoothing, ignore_index):
    rows, vocab = logits.shape
    blk = _row_block(rows, vocab)
    pad = (-rows) % blk
    if pad:
        logits = jnp.pad(logits, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad), constant_values=ignore_index)
    labels2d = labels.astype(jnp.int32)[:, None]
    grid = (logits.shape[0] // blk,)

    row_spec = pl.BlockSpec((blk, vocab), lambda i: (i, 0), memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((blk, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)

    loss, lse = pl.pallas_call(
        functools.partial(
            _xent_fwd_kernel, smoothing=smoothing, ignore_index=ignore_index
        ),
        grid=grid,
        in_specs=[row_spec, col_spec],
        out_specs=[col_spec, col_spec],
        out_shape=[
            jax.ShapeDtypeStruct((logits.shape[0], 1), jnp.float32),
            jax.ShapeDtypeStruct((logits.shape[0], 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(logits, labels2d)
    return loss[:rows, 0], lse[:rows]


@functools.partial(jax.jit, static_argnames=("smoothing", "ignore_index"))
def _bwd(g, logits, labels, lse, *, smoothing, ignore_index):
    rows, vocab = logits.shape
    blk = _row_block(rows, vocab)
    pad = (-rows) % blk
    g2d = g.astype(jnp.float32)[:, None]
    if pad:
        logits = jnp.pad(logits, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad), constant_values=ignore_index)
        g2d = jnp.pad(g2d, ((0, pad), (0, 0)))
        lse = jnp.pad(lse, ((0, pad), (0, 0)))
    labels2d = labels.astype(jnp.int32)[:, None]
    grid = (logits.shape[0] // blk,)

    row_spec = pl.BlockSpec((blk, vocab), lambda i: (i, 0), memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((blk, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)

    dx = pl.pallas_call(
        functools.partial(
            _xent_bwd_kernel, smoothing=smoothing, ignore_index=ignore_index
        ),
        grid=grid,
        in_specs=[col_spec, row_spec, col_spec, col_spec],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct(logits.shape, logits.dtype),
        interpret=_interpret(),
    )(g2d, logits, labels2d, lse)
    return dx[:rows]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _softmax_xentropy(logits, labels, smoothing, ignore_index):
    loss, _ = _fwd(logits, labels, smoothing=smoothing, ignore_index=ignore_index)
    return loss


def _sx_fwd(logits, labels, smoothing, ignore_index):
    loss, lse = _fwd(logits, labels, smoothing=smoothing, ignore_index=ignore_index)
    return loss, (logits, labels, lse)


def _sx_bwd(smoothing, ignore_index, res, g):
    logits, labels, lse = res
    dx = _bwd(g, logits, labels, lse, smoothing=smoothing, ignore_index=ignore_index)
    return dx, None


_softmax_xentropy.defvjp(_sx_fwd, _sx_bwd)


def _xla_xentropy(logits, labels, smoothing, ignore_index):
    x = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(x, axis=-1)
    x_y = jnp.take_along_axis(
        x, jnp.clip(labels, 0, x.shape[-1] - 1)[:, None].astype(jnp.int32), axis=-1
    )[:, 0]
    nll = lse - x_y
    if smoothing > 0.0:
        loss = (1.0 - smoothing) * nll + smoothing * (lse - jnp.mean(x, axis=-1))
    else:
        loss = nll
    return jnp.where(labels != ignore_index, loss, 0.0)


def softmax_cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    smoothing: float = 0.0,
    ignore_index: int = -100,
    *,
    impl: str = "auto",
) -> jax.Array:
    """Per-row fused CE loss (SoftmaxCrossEntropyLoss,
    apex/contrib/xentropy/softmax_xentropy.py:4-28).

    ``logits``: (..., vocab); ``labels``: (...,) int. Returns per-row losses
    (0 for ignored rows); reduce with mean/sum as the caller wishes, dividing
    by the valid count for an ignore-aware mean."""
    shape = labels.shape
    l2 = logits.reshape(-1, logits.shape[-1])
    y = labels.reshape(-1)
    if _resolve_impl(impl) == "xla":
        out = _xla_xentropy(l2, y, smoothing, ignore_index)
    else:
        out = _softmax_xentropy(l2, y, float(smoothing), int(ignore_index))
    return out.reshape(shape)


def softmax_cross_entropy_reference(logits, labels, smoothing=0.0, ignore_index=-100):
    shape = labels.shape
    out = _xla_xentropy(
        logits.reshape(-1, logits.shape[-1]), labels.reshape(-1), smoothing, ignore_index
    )
    return out.reshape(shape)
