"""apex_tpu.normalization — fused LayerNorm/RMSNorm modules.

Reference: apex/normalization/fused_layer_norm.py (FusedLayerNorm :204,
FusedRMSNorm :300, MixedFused variants :398-436) over
csrc/layer_norm_cuda_kernel.cu. Backed here by the Pallas kernels in
apex_tpu.ops.layer_norm.
"""

from apex_tpu.normalization.fused_layer_norm import (  # noqa: F401
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
    MixedFusedRMSNorm,
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
)
