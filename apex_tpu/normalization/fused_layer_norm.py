"""Fused normalization modules (flax) over the Pallas kernels.

Reference surface (apex/normalization/fused_layer_norm.py):
- ``FusedLayerNorm(normalized_shape, eps, elementwise_affine)`` (:204-297)
- ``FusedRMSNorm`` (:300-396)
- ``MixedFusedLayerNorm/RMSNorm`` — fp16/bf16 inputs with fp32 affine params
  (:398-436); in JAX this is just params kept fp32 while inputs arrive half,
  which the kernels support natively (stats are always fp32).
- functional forms ``fused_layer_norm(_affine)`` / ``fused_rms_norm(_affine)``
  (:168-202).

The reference normalizes over a trailing ``normalized_shape`` tuple; the
kernels normalize over one trailing dim, so inputs are flattened to
``(..., prod(normalized_shape))`` and restored — same math, contiguous
layout.
"""

from __future__ import annotations

from typing import Iterable, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from flax import linen as nn

from apex_tpu.ops import layer_norm as _ops

Shape = Union[int, Sequence[int]]


def _canon_shape(normalized_shape: Shape) -> Tuple[int, ...]:
    if isinstance(normalized_shape, int):
        return (normalized_shape,)
    return tuple(int(s) for s in normalized_shape)


def _flatten(x, nshape):
    n = 1
    for s in nshape:
        n *= s
    if x.shape[-len(nshape) :] != nshape:
        raise ValueError(
            f"input trailing dims {x.shape[-len(nshape):]} != normalized_shape {nshape}"
        )
    return x.reshape(x.shape[: -len(nshape)] + (n,)), x.shape


def fused_layer_norm_affine(x, weight, bias, normalized_shape: Shape, eps=1e-5, *, impl="auto"):
    nshape = _canon_shape(normalized_shape)
    x2, orig = _flatten(x, nshape)
    y = _ops.layer_norm(x2, weight.reshape(-1), bias.reshape(-1), eps, impl=impl)
    return y.reshape(orig)


def fused_layer_norm(x, normalized_shape: Shape, eps=1e-5, *, impl="auto"):
    nshape = _canon_shape(normalized_shape)
    x2, orig = _flatten(x, nshape)
    return _ops.layer_norm(x2, None, None, eps, impl=impl).reshape(orig)


def fused_rms_norm_affine(x, weight, normalized_shape: Shape, eps=1e-5, *, impl="auto"):
    nshape = _canon_shape(normalized_shape)
    x2, orig = _flatten(x, nshape)
    return _ops.rms_norm(x2, weight.reshape(-1), eps, impl=impl).reshape(orig)


def fused_rms_norm(x, normalized_shape: Shape, eps=1e-5, *, impl="auto"):
    nshape = _canon_shape(normalized_shape)
    x2, orig = _flatten(x, nshape)
    return _ops.rms_norm(x2, None, eps, impl=impl).reshape(orig)


class FusedLayerNorm(nn.Module):
    """Drop-in FusedLayerNorm module (fused_layer_norm.py:204-297).

    ``param_dtype`` defaults to fp32 — with half inputs this *is* the
    MixedFused variant (:398-416)."""

    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    param_dtype: jnp.dtype = jnp.float32
    impl: str = "auto"

    @nn.compact
    def __call__(self, x):
        nshape = _canon_shape(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("scale", nn.initializers.ones, nshape, self.param_dtype)
            bias = self.param("bias", nn.initializers.zeros, nshape, self.param_dtype)
            return fused_layer_norm_affine(x, weight, bias, nshape, self.eps, impl=self.impl)
        return fused_layer_norm(x, nshape, self.eps, impl=self.impl)


class FusedRMSNorm(nn.Module):
    """Drop-in FusedRMSNorm module (fused_layer_norm.py:300-396)."""

    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    param_dtype: jnp.dtype = jnp.float32
    impl: str = "auto"

    @nn.compact
    def __call__(self, x):
        nshape = _canon_shape(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("scale", nn.initializers.ones, nshape, self.param_dtype)
            return fused_rms_norm_affine(x, weight, nshape, self.eps, impl=self.impl)
        return fused_rms_norm(x, nshape, self.eps, impl=self.impl)


# The Mixed variants differ from the base ones only in forcing fp32 affine
# params with half activations (fused_layer_norm.py:398-436) — the default
# param_dtype here. Aliases keep the reference's import surface.
MixedFusedLayerNorm = FusedLayerNorm
MixedFusedRMSNorm = FusedRMSNorm
