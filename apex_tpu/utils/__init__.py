"""Shared utilities: logging, checkpointing, timers."""
