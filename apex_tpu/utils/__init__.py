"""Shared utilities: logging, jax-version compat shims, small nn helpers.

No reference-file citation: host-side conveniences the reference gets from
torch builtins; each submodule documents its own mapping where one exists.
"""
