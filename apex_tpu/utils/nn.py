"""Small shared nn helpers.

No reference-file citation of their own: :func:`inverted_dropout` preserves
``torch.nn.functional.dropout`` semantics (inverted scaling, identity at
eval) that the reference's modules rely on implicitly; callers cite the
module whose behavior they reproduce.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def inverted_dropout(x: jax.Array, key: Optional[jax.Array], rate: float) -> jax.Array:
    """Standard inverted dropout: identity when ``key is None`` or
    ``rate == 0`` (eval mode), else zero with prob ``rate`` and scale the
    survivors by ``1/keep``. One home for the pattern used across the model
    zoo, MHA modules, RNNs, and the transducer joint."""
    if key is None or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
