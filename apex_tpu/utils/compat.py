"""jax version compatibility: one place for API renames we depend on.

The framework is written against the modern surface (``jax.shard_map``
with ``check_vma=``); older jax (< 0.5) ships the same functionality as
``jax.experimental.shard_map.shard_map`` with the flag spelled
``check_rep=``. :func:`ensure_shard_map` installs a thin adapter under
``jax.shard_map`` when the top-level name is missing, translating the
flag name — semantics are identical (the replication/varying-manual-axes
check was renamed, not changed). Idempotent and a no-op on modern jax,
so the adapter can be called from every entrypoint cheaply.

Called explicitly from the runnable entrypoints (``__graft_entry__``,
``bench.py``, ``examples/``, ``benchmarks/``) rather than from the
package ``__init__``: the tier-1 suite's wall-clock budget is sized to
the container's native jax surface, and silently widening what every
test exercises from a package import is not this module's call to make.
"""

from __future__ import annotations

import functools


def ensure_shard_map() -> bool:
    """Install the ``jax.shard_map`` adapter if missing; returns True
    when the modern API is available (natively or via the adapter)."""
    import jax

    try:
        if getattr(jax, "shard_map", None) is not None:
            return True
    except Exception:  # noqa: BLE001 - deprecation getattr may raise
        pass
    try:
        from jax.experimental.shard_map import shard_map as _legacy
    except Exception:  # noqa: BLE001 - neither spelling exists
        return False

    @functools.wraps(_legacy)
    def shard_map(f=None, /, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if f is None:  # modern jax allows partial application
            return lambda g: shard_map(g, **kwargs)
        return _legacy(f, **kwargs)

    jax.shard_map = shard_map
    return True


def ensure_lax_axis_size() -> bool:
    """Install ``lax.axis_size`` when missing (jax < 0.4.38): the
    historical spelling is ``lax.psum(1, axis)``, which returns a STATIC
    python int inside any context that binds the axis — identical
    semantics, tuple axes included (the psum over a tuple multiplies
    through)."""
    from jax import lax

    if getattr(lax, "axis_size", None) is not None:
        return True

    def axis_size(axis_name):
        return lax.psum(1, axis_name)

    lax.axis_size = axis_size
    return True


def ensure_jax_compat() -> bool:
    """Install every adapter an entrypoint needs; True iff all landed."""
    return bool(ensure_shard_map()) and bool(ensure_lax_axis_size())
