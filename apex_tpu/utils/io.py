"""Atomic JSON artifact writes: temp file + rename in one directory.

Evidence scripts (``benchmarks/*.py``) and crash dumps
(``monitor/flight.py``) publish single-JSON artifacts that later gates
consume (``report compare``, the driver's evidence checks). A plain
``open(path, "w")`` torn by a crash or a watchdog SIGKILL leaves a
truncated file that poisons every later consumer; ``os.replace`` of a
fully-written temp file in the same directory is atomic on POSIX, so a
reader sees either the old artifact or the complete new one — never a
torn half. Same discipline as ``monitor/watchdog.py``'s checkpoint
protocol, shared here so every ``out/*.json`` writer uses one copy.

No reference-file citation: NVIDIA Apex has no evidence-artifact layer;
this is repo-local tooling discipline.
"""

from __future__ import annotations

import json
import os
from typing import Any


def atomic_write_json(path: str, obj: Any, *, indent: int = 1,
                      default=str) -> str:
    """Write ``obj`` as JSON to ``path`` atomically (tmp + rename).

    The temp file lives in the target's directory so the rename never
    crosses filesystems. Raises on serialization/IO errors (an evidence
    script SHOULD fail loudly when it cannot publish its artifact) but
    never leaves a torn ``path`` behind — the temp file is unlinked on
    failure. Returns ``path``.
    """
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=indent, default=default)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path
