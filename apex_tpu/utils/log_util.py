"""Rank-aware library logging.

Reference: apex/__init__.py:27-39 installs a ``RankInfoFormatter`` injecting the
(dp, tp, pp, vpp) rank tuple into every record (rank info from
apex/transformer/parallel_state.py:186-195, apex/transformer/log_util.py).
Here ranks come from ``jax.process_index`` and the active parallel context.
"""

from __future__ import annotations

import logging


class RankInfoFilter(logging.Filter):
    def filter(self, record):
        try:
            import jax

            record.rank = jax.process_index()
        except Exception:
            record.rank = 0
        try:
            from apex_tpu.transformer import parallel_state

            record.rank_info = parallel_state.get_rank_info_str()
        except Exception:
            record.rank_info = ""
        return True


def get_logger(name: str = "apex_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname)s [proc %(rank)s%(rank_info)s] %(name)s: %(message)s"
            )
        )
        handler.addFilter(RankInfoFilter())
        logger.addHandler(handler)
        logger.propagate = False
    return logger


def maybe_print(msg: str, rank0: bool = False):
    """Print helper mirroring apex/amp/_amp_state.py:39-51."""
    try:
        import jax

        if rank0 and jax.process_index() != 0:
            return
    except Exception:
        pass
    print(msg, flush=True)
