"""Fused optimizers — TPU-native equivalents of ``apex.optimizers``.

The reference's optimizers batch per-parameter updates into single
``multi_tensor_*`` CUDA launches (apex/optimizers/fused_adam.py:117-170 etc.,
csrc/multi_tensor_adam.cu, csrc/multi_tensor_lamb.cu, ...). Under XLA, an
optimizer whose update is a single traced ``tree.map`` compiles to the same
thing — one fused elementwise pass over all parameters — so these are
implemented as optax-compatible ``GradientTransformation`` factories, with
thin class aliases matching the reference names.

``scale`` / overflow interop (the deprecated contrib optimizers' explicit
``scale`` arg, apex/contrib/optimizers/fused_adam.py:90+) lives one level up
in ``apex_tpu.amp.MixedPrecisionOptimizer``.
"""

from apex_tpu.optimizers.fused_adam import fused_adam, FusedAdam  # noqa: F401
from apex_tpu.optimizers.fused_lamb import fused_lamb, FusedLAMB  # noqa: F401
from apex_tpu.optimizers.fused_mixed_precision_lamb import (  # noqa: F401
    FusedMixedPrecisionLamb,
    FusedMixedPrecisionLambState,
)
from apex_tpu.optimizers.fused_sgd import fused_sgd, FusedSGD  # noqa: F401
from apex_tpu.optimizers.fused_novograd import fused_novograd, FusedNovoGrad  # noqa: F401
from apex_tpu.optimizers.fused_adagrad import fused_adagrad, FusedAdagrad  # noqa: F401
from apex_tpu.optimizers.larc import larc, LARC  # noqa: F401
from apex_tpu.optimizers.distributed import (  # noqa: F401
    DistributedFusedAdam,
    DistributedFusedLAMB,
    DistributedFusedSGD,
    abstract_state,
    distributed_fused,
    sharded_state_shapes,
    state_specs,
)
