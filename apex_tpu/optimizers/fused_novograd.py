"""FusedNovoGrad — Adam variant with per-tensor (layer-wise) second moments.

Reference: apex/optimizers/fused_novograd.py + csrc/multi_tensor_novograd.cu:
``v`` is a scalar per tensor (norm of the grad), first step initialises
``v = ||g||^2`` (``init_zero=False`` default), ``norm_type=2``, decoupled or
L2 weight decay via ``reg_inside_moment``.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers._common import (
    ClassOptimizer,
    cast_like,
    multi_tree_map,
    tree_zeros_like,
)


class FusedNovoGradState(NamedTuple):
    step: jax.Array
    exp_avg: optax.Params
    exp_avg_sq: optax.Params  # scalar per tensor


def fused_novograd(
    lr: float = 1e-3,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    grad_averaging: bool = True,
    init_zero: bool = False,
    reg_inside_moment: bool = False,
    bias_correction: bool = True,
) -> optax.GradientTransformation:
    beta1, beta2 = betas

    def init_fn(params):
        return FusedNovoGradState(
            step=jnp.zeros([], jnp.int32),
            exp_avg=tree_zeros_like(params),
            exp_avg_sq=jax.tree.map(lambda p: jnp.zeros([], jnp.float32), params),
        )

    def update_fn(grads, state, params=None, *, lr_t=None):
        if params is None:
            raise ValueError("fused_novograd requires params")
        step = state.step + 1
        step_lr = jnp.asarray(lr_t if lr_t is not None else lr, jnp.float32)
        beta1_grad = (1.0 - beta1) if grad_averaging else 1.0
        first = state.step == 0
        if bias_correction:
            bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
            bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        def _upd(g, p, m, v):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            gnorm_sq = jnp.sum(jnp.square(g32))
            if init_zero:
                v_new = beta2 * v + (1.0 - beta2) * gnorm_sq
            else:
                v_new = jnp.where(first, gnorm_sq, beta2 * v + (1.0 - beta2) * gnorm_sq)
            denom = jnp.sqrt(v_new / bc2) + eps
            gn = g32 / denom
            if weight_decay != 0.0 and reg_inside_moment:
                gn = gn + weight_decay * p32
            m_new = beta1 * m + beta1_grad * gn
            upd = m_new / bc1
            if weight_decay != 0.0 and not reg_inside_moment:
                upd = upd + weight_decay * p32
            return (-step_lr * upd, m_new, v_new)

        updates, new_m, new_v = multi_tree_map(
            _upd, grads, params, state.exp_avg, state.exp_avg_sq, n_out=3
        )
        return cast_like(updates, params), FusedNovoGradState(step, new_m, new_v)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedNovoGrad(ClassOptimizer):
    def __init__(
        self,
        lr=1e-3,
        bias_correction=True,
        betas=(0.9, 0.999),
        eps=1e-8,
        weight_decay=0.0,
        grad_averaging=True,
        init_zero=False,
        reg_inside_moment=False,
        **_ignored,
    ):
        super().__init__(
            fused_novograd(
                lr=lr,
                betas=betas,
                eps=eps,
                weight_decay=weight_decay,
                grad_averaging=grad_averaging,
                init_zero=init_zero,
                reg_inside_moment=reg_inside_moment,
                bias_correction=bias_correction,
            ),
            lr=lr,
        )
