"""LARC — layer-wise adaptive rate control as a gradient-transform wrapper.

Reference: apex/parallel/LARC.py:5-107 — wraps any optimizer and rescales each
param's gradient by the adaptive LR
``trust_coefficient * ||p|| / (||g|| + weight_decay * ||p|| + eps)`` before
the inner step, either clipped against the base LR (``clip=True``) or used as
a multiplicative scale. Implemented here as an optax chain-style wrapper.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers._common import ClassOptimizer


def larc(
    inner: optax.GradientTransformation,
    trust_coefficient: float = 0.02,
    clip: bool = True,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    base_lr: Optional[float] = None,
) -> optax.GradientTransformation:
    """Wrap ``inner`` with LARC grad rescaling (LARC.py:78-104).

    ``base_lr`` is the LR the inner transform will apply; the reference reads
    it live from ``group['lr']`` (LARC.py:96), but an optax transform hides
    its LR, so clip mode — ratio ``min(adaptive_lr / lr, 1)`` — requires it
    explicitly (the ``LARC`` class fills it from ``optimizer.lr``).
    """
    if clip and base_lr is None:
        raise ValueError(
            "larc(clip=True) needs base_lr (the inner optimizer's learning "
            "rate) to form min(adaptive_lr / lr, 1); pass base_lr= or use the "
            "LARC class with an apex_tpu fused optimizer."
        )

    def init_fn(params):
        return inner.init(params)

    def update_fn(grads, state, params=None, **extra):
        if params is None:
            raise ValueError("larc requires params")
        # An lr_t runtime override reaches the inner optimizer through
        # **extra, so the clip denominator must track it (the reference reads
        # group['lr'] live each step, LARC.py:96).
        step_lr = extra.get("lr_t", base_lr)
        if step_lr is None:
            step_lr = base_lr

        def _rescale(g, p):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            pnorm = jnp.sqrt(jnp.sum(jnp.square(p32)))
            gnorm = jnp.sqrt(jnp.sum(jnp.square(g32)))
            adaptive_lr = trust_coefficient * pnorm / (gnorm + weight_decay * pnorm + eps)
            if clip:
                adaptive_lr = jnp.minimum(adaptive_lr / step_lr, 1.0)
            # the reference only touches the grad inside the nonzero-norms
            # branch (LARC.py:92-102): zero-grad params stay untouched.
            active = (pnorm > 0) & (gnorm > 0)
            scaled = (g32 + weight_decay * p32) * adaptive_lr
            return jnp.where(active, scaled, g32).astype(g.dtype)

        grads = jax.tree.map(_rescale, grads, params)
        return inner.update(grads, state, params, **extra)

    return optax.GradientTransformation(init_fn, update_fn)


class LARC(ClassOptimizer):
    def __init__(
        self,
        optimizer,
        trust_coefficient=0.02,
        clip=True,
        eps=1e-8,
        weight_decay=0.0,
        base_lr=None,
    ):
        inner = optimizer.transform if isinstance(optimizer, ClassOptimizer) else optimizer
        if base_lr is None:
            base_lr = getattr(optimizer, "lr", None)
        super().__init__(
            larc(
                inner,
                trust_coefficient=trust_coefficient,
                clip=clip,
                eps=eps,
                weight_decay=weight_decay,
                base_lr=base_lr,
            ),
            lr=base_lr,
        )
