"""FusedSGD — SGD + momentum/Nesterov as one fused tree update.

Reference: apex/optimizers/fused_sgd.py + csrc/multi_tensor_sgd_kernel.cu
(momentum/nesterov/dampening, ``wd_after_momentum`` flag, first-run momentum
init). The reference's amp interop (``materialize_master_grads``,
``most_recent_scale``, fused_sgd.py:79-96,138-224) deferred grad unscaling
into the kernel; here unscaling is handled by the amp layer and fuses in XLA
anyway.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers._common import (
    ClassOptimizer,
    cast_like,
    multi_tree_map,
    tree_zeros_like,
)


class FusedSGDState(NamedTuple):
    step: jax.Array
    momentum_buf: optax.Params


def fused_sgd(
    lr: float = 1e-3,
    momentum: float = 0.0,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
    wd_after_momentum: bool = False,
) -> optax.GradientTransformation:
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError("Nesterov momentum requires a momentum and zero dampening")

    def init_fn(params):
        return FusedSGDState(
            step=jnp.zeros([], jnp.int32),
            momentum_buf=tree_zeros_like(params),
        )

    def update_fn(grads, state, params=None, *, lr_t=None):
        if params is None:
            raise ValueError("fused_sgd requires params")
        step = state.step + 1
        step_lr = jnp.asarray(lr_t if lr_t is not None else lr, jnp.float32)
        first_run = state.step == 0

        def _upd(g, p, buf):
            d32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay != 0.0 and not wd_after_momentum:
                d32 = d32 + weight_decay * p32
            if momentum != 0.0:
                # first_run: momentum buffer initialises to the grad itself
                # (multi_tensor_sgd_kernel.cu first_run flag).
                buf_new = jnp.where(
                    first_run, d32, momentum * buf + (1.0 - dampening) * d32
                )
                d32 = d32 + momentum * buf_new if nesterov else buf_new
            else:
                buf_new = buf
            if weight_decay != 0.0 and wd_after_momentum:
                d32 = d32 + weight_decay * p32
            return -step_lr * d32, buf_new

        updates, new_buf = multi_tree_map(_upd, grads, params, state.momentum_buf, n_out=2)
        return cast_like(updates, params), FusedSGDState(step, new_buf)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedSGD(ClassOptimizer):
    def __init__(
        self,
        lr=1e-3,
        momentum=0.0,
        dampening=0.0,
        weight_decay=0.0,
        nesterov=False,
        wd_after_momentum=False,
        **_ignored,
    ):
        super().__init__(
            fused_sgd(
                lr=lr,
                momentum=momentum,
                dampening=dampening,
                weight_decay=weight_decay,
                nesterov=nesterov,
                wd_after_momentum=wd_after_momentum,
            ),
            lr=lr,
        )
