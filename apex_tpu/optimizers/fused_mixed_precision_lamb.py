"""FusedMixedPrecisionLamb — LAMB that owns its fp32 master weights.

Reference: apex/optimizers/fused_mixed_precision_lamb.py (kernel
csrc/multi_tensor_lamb_mp.cu). Unlike ``FusedLAMB`` (whose master-weight
handling lives one level up in ``amp.MixedPrecisionOptimizer``), this variant
carries the full-precision parameter copies *inside* the optimizer state and
takes tensor-valued ``lr`` / ``scale`` / ``found_inf`` so a training step runs
with zero host synchronization:

- masters are cloned lazily at init from reduced-precision leaves
  (``_setup_full_precision_params``, reference :117-127);
- grads arrive *scaled*; the kernel unscales with ``inv_scale`` and the
  global-norm clip compares against ``max_grad_norm * scale`` (reference
  :181-189), which is mathematically the unscaled clip;
- ``step`` increments only on non-overflow steps
  (``group['step'] += (overflow != 1)``, reference :199-201) and the whole
  update is skipped under ``lax.cond`` when ``found_inf`` is set;
- the updated fp32 masters are written back out in the model dtype
  (state list (4) "params reduced_dtype" of the _mp kernel).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.ops.multi_tensor import tree_l2norm, tree_nonfinite
from apex_tpu.optimizers._common import (
    lamb_leaf_update,
    multi_tree_map,
    tree_zeros_like,
)


class FusedMixedPrecisionLambState(NamedTuple):
    step: jax.Array
    exp_avg: optax.Params
    exp_avg_sq: optax.Params
    #: fp32 full-precision copies of the model params (the reference's
    #: ``param_groups_full_precision``); updated in place of the model params.
    master: optax.Params


class FusedMixedPrecisionLamb:
    """Sync-free mixed-precision LAMB.

    Usage::

        opt = FusedMixedPrecisionLamb(lr=1e-3, reduced_precision_dtype=jnp.bfloat16)
        state = opt.init(model_params)           # clones fp32 masters
        new_params, state = opt.step(
            state, model_params, scaled_grads, scale=loss_scale)

    ``step`` returns model params in their original (reduced) dtype; the fp32
    source of truth lives in ``state.master``.
    """

    def __init__(
        self,
        lr: float = 1e-3,
        step: int = 0,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        amsgrad: bool = False,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        reduced_precision_dtype: Optional[Any] = None,
    ):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        if not adam_w_mode:
            raise RuntimeError(
                "FusedMixedPrecisionLamb only supports adam_w_mode (decoupled "
                "wd), as the reference kernel does."
            )
        self.lr = lr
        self._step0 = step
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self.reduced_precision_dtype = reduced_precision_dtype

    def _is_reduced(self, p) -> bool:
        return (
            self.reduced_precision_dtype is not None
            and p.dtype == jnp.dtype(self.reduced_precision_dtype)
        )

    def init(self, model_params) -> FusedMixedPrecisionLambState:
        # Masters exist only for reduced-precision leaves; fp32 leaves are
        # updated directly (reference keeps None placeholders, :121-126 —
        # here the "placeholder" is the fp32 leaf itself).
        master = jax.tree.map(
            lambda p: p.astype(jnp.float32) if self._is_reduced(p) else p,
            model_params,
        )
        return FusedMixedPrecisionLambState(
            step=jnp.asarray(self._step0, jnp.int32),
            exp_avg=tree_zeros_like(model_params),
            exp_avg_sq=tree_zeros_like(model_params),
            master=master,
        )

    def step(
        self,
        state: FusedMixedPrecisionLambState,
        model_params,
        grads,
        *,
        lr_t=None,
        scale=None,
        found_inf=None,
    ):
        """One LAMB step. ``grads`` are grads of the ``scale``-scaled loss
        (pass ``scale=None`` for unscaled grads). Returns
        ``(new_model_params, new_state)``."""
        beta1, beta2 = self.betas
        step_lr = jnp.asarray(lr_t if lr_t is not None else self.lr, jnp.float32)
        scale = jnp.asarray(1.0 if scale is None else scale, jnp.float32)
        inv_scale = 1.0 / scale
        if found_inf is None:
            found_inf = tree_nonfinite(grads)
        found_inf = jnp.asarray(found_inf, jnp.bool_)

        # step advances only on clean steps (reference :199-201).
        new_step = state.step + jnp.where(found_inf, 0, 1).astype(jnp.int32)
        step_f = new_step.astype(jnp.float32)
        if self.bias_correction:
            bc1 = 1.0 - beta1 ** step_f
            bc2 = 1.0 - beta2 ** step_f
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        beta1_grad = (1.0 - beta1) if self.grad_averaging else 1.0

        # Global norm of the *scaled* grads vs max_grad_norm * scale
        # (reference :181-189) == the unscaled-gradient clip factor.
        grad_norm = tree_l2norm(grads)
        if self.max_grad_norm and self.max_grad_norm > 0:
            clip = jnp.maximum(1.0, grad_norm / (self.max_grad_norm * scale))
        else:
            clip = jnp.asarray(1.0, jnp.float32)

        def _upd(g, p32, m, v):
            g32 = g.astype(jnp.float32) * inv_scale / clip
            scaled_upd, m_new, v_new = lamb_leaf_update(
                g32,
                p32,
                m,
                v,
                beta1=beta1,
                beta2=beta2,
                beta1_grad=beta1_grad,
                bc1=bc1,
                bc2=bc2,
                eps=self.eps,
                weight_decay=self.weight_decay,
                use_nvlamb=self.use_nvlamb,
            )
            return (p32 - step_lr * scaled_upd, m_new, v_new)

        def _do_step(operand):
            master, m, v = operand
            return multi_tree_map(_upd, grads, master, m, v, n_out=3)

        def _skip_step(operand):
            return operand

        new_master, new_m, new_v = jax.lax.cond(
            found_inf,
            _skip_step,
            _do_step,
            (state.master, state.exp_avg, state.exp_avg_sq),
        )
        # fp32 master -> reduced model copy-out (state list (4) of the kernel).
        new_model = jax.tree.map(
            lambda mp, p: mp.astype(p.dtype), new_master, model_params
        )
        return new_model, FusedMixedPrecisionLambState(
            step=new_step, exp_avg=new_m, exp_avg_sq=new_v, master=new_master
        )
