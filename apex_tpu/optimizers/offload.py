"""Host-offloaded ZeRO optimizer state with bucketed H2D prefetch.

Reference: apex/contrib/optimizers/distributed_fused_adam.py:55-477 — the
contrib optimizer's bucketed state layout (grads reduce and the update
applies one contiguous bucket at a time, overlapping communication with
the rest of the step) and its CPU-offload deployment point: the fp32
masters and moments are COLD between steps, touched only inside
``apply_gradients``, so at pod scale they live in host RAM and stream
through HBM one bucket at a time. The JAX spelling here:

- :class:`HostOffloadedZero` wraps a ``MixedPrecisionOptimizer`` whose
  ``zero_axis`` (optionally ``dcn_axis``) is set: the sharded state a
  resident step would keep in HBM — fp32 master chunks, inner moments,
  the error-feedback residual — is held as host numpy between steps
  (:class:`HostOffloadState`), split into ``num_buckets`` contiguous
  leaf buckets.
- ``apply_gradients`` runs phase A (unscale + overflow pmax over the
  whole zero group) as one jitted shard_map, then drives the buckets:
  bucket ``b+1``'s ``jax.device_put`` (async H2D) is issued BEFORE
  bucket ``b``'s jitted scatter→update→gather program runs, so the
  transfer hides under the previous bucket's compute — the same
  double-buffering idiom as ``models/_transformer._prefetched_zero3_drive``
  (there: ZeRO-3 param gathers under layer compute; here: H2D copies
  under the optimizer update). ``offload.h2d`` / ``offload.apply``
  tracer spans make the overlap auditable in the timeline.
- Bit-identity: the scatter, inner update, overflow select-back, and
  gather run per bucket with exactly the per-leaf arithmetic of
  ``MixedPrecisionOptimizer._apply_zero`` — per-leaf inner transforms
  (the Adam family: elementwise moments + a per-state step counter that
  increments identically in every bucket) make the bucketed step
  bit-identical to the resident whole-tree step
  (tests/test_hierarchy.py pins it).

Scope: ZeRO levels 1/2 with every param replicated over the zero group
(no expert-sharded MoE leaves — their masters are the local shard and
never leave the device cheaply) and no stochastic rounding (the dither
key is one per-rank stream, not bucketable state).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.amp.frontend import (
    MixedPrecisionOptimizer,
    _scaler_from_policy,
    _spec_axis_names,
)
from apex_tpu.monitor.tracing import get_tracer, maybe_span
from apex_tpu.optimizers.distributed import (
    chunk_size,
    gather_leaf,
    local_chunk,
    scatter_chunk,
)


class HostOffloadState:
    """Between-steps optimizer state: per-bucket HOST trees + the
    device-resident loss scaler.

    ``host`` is a list of ``{"master": ..., "inner": ..., "residual": ...}``
    numpy trees (global arrays — the universal chunk layout concatenated
    across ranks); only the scaler (a few scalars) stays on device. NOT a
    jax pytree: it never crosses a jit boundary whole — buckets stream
    through ``device_put``/``device_get`` one at a time."""

    __slots__ = ("host", "scaler")

    def __init__(self, host: List[Dict[str, Any]], scaler):
        self.host = host
        self.scaler = scaler

    def hbm_resident_bytes(self) -> int:
        """Peak optimizer-state HBM at any instant: the two largest
        buckets (the in-flight bucket + its prefetched successor)."""
        sizes = sorted((_tree_bytes(b) for b in self.host), reverse=True)
        return sum(sizes[:2])

    def host_bytes(self) -> int:
        return sum(_tree_bytes(b) for b in self.host)


def _tree_bytes(tree) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


class HostOffloadedZero:
    """Bucketed host-offload driver around a ZeRO
    :class:`~apex_tpu.amp.frontend.MixedPrecisionOptimizer`.

    >>> off = HostOffloadedZero(mp_opt, mesh, param_specs, num_buckets=2)
    >>> state = off.init(params)               # masters land in host RAM
    >>> params, state, metrics = off.apply_gradients(state, params, grads)

    ``scaled_grads`` leaves carry a leading group axis (size
    ``n_dcn * n_ici``, sharded ``P(group)``) stacking each rank's own
    unreduced local-mean grad — the global spelling of the per-rank grads
    a resident step sees inside its shard_map.
    """

    def __init__(
        self,
        mp_opt: MixedPrecisionOptimizer,
        mesh,
        param_specs,
        *,
        num_buckets: int = 2,
        found_inf_reducer: Optional[Callable] = None,
    ):
        if mp_opt.zero_axis is None:
            raise ValueError("HostOffloadedZero requires zero_axis: the "
                             "offloaded state IS the ZeRO chunk tree")
        if mp_opt.zero_level >= 3:
            raise ValueError(
                "HostOffloadedZero composes with ZeRO levels 1/2 only: at "
                "level 3 the per-layer gather transposes deliver grads "
                "inside the backward, not in apply_gradients — there is "
                "no single apply phase to stream buckets through")
        if mp_opt.stochastic_rounding:
            raise ValueError("stochastic_rounding does not compose with "
                             "the offload driver: the dither key is one "
                             "per-rank stream, not per-bucket state")
        self.mp = mp_opt
        self.mesh = mesh
        self.num_buckets = max(int(num_buckets), 1)
        self._found_inf_reducer = found_inf_reducer
        #: host-side mirror of the traced group helpers
        self._group: Tuple[str, ...] = (
            (mp_opt.dcn_axis, mp_opt.zero_axis)
            if mp_opt.dcn_axis is not None else (mp_opt.zero_axis,))
        self._n = 1
        for ax in self._group:
            self._n *= mesh.shape[ax]
        self._param_specs = param_specs
        self._built = False

    # -- host-side layout ----------------------------------------------------
    def _spec_leaves(self, leaves):
        if self._param_specs is None:
            return [None] * len(leaves)
        spec_leaves = jax.tree.leaves(
            self._param_specs, is_leaf=lambda x: isinstance(x, P))
        if len(spec_leaves) != len(leaves):
            raise ValueError(
                f"param_specs tree has {len(spec_leaves)} specs for "
                f"{len(leaves)} params")
        return spec_leaves

    def _local_shape(self, shape, spec) -> Tuple[int, ...]:
        out = list(int(d) for d in shape)
        for d, entry in enumerate(spec or ()):
            for ax in _spec_axis_names(entry):
                if ax in self._group:
                    raise ValueError(
                        f"param of shape {tuple(shape)} is sharded over "
                        f"the zero-group axis {ax!r}: the offload driver "
                        f"requires every param replicated over the group "
                        f"(expert-sharded MoE leaves stay resident — use "
                        f"the in-HBM MixedPrecisionOptimizer for them)")
                out[d] //= self.mesh.shape[ax]
        return tuple(out)

    def _build(self, model_params) -> None:
        """One-time layout + program build from the param tree."""
        mp, mesh = self.mp, self.mesh
        leaves, treedef = jax.tree.flatten(model_params)
        spec_leaves = self._spec_leaves(leaves)
        self._treedef = treedef
        self._leaf_specs = [s if s is not None else P() for s in spec_leaves]
        self._leaf_local = [self._local_shape(p.shape, s)
                            for p, s in zip(leaves, spec_leaves)]
        self._leaf_structs = [jax.ShapeDtypeStruct(p.shape, p.dtype)
                              for p in leaves]

        # contiguous buckets balanced by leaf bytes (flat order — the
        # contrib optimizer's contiguous-range bucketing)
        total = sum(p.size * p.dtype.itemsize for p in leaves)
        n_buckets = min(self.num_buckets, len(leaves))
        target = total / n_buckets
        buckets: List[List[int]] = [[]]
        acc = 0
        for i, p in enumerate(leaves):
            if (acc >= target * len(buckets)
                    and len(buckets) < n_buckets and buckets[-1]):
                buckets.append([])
            buckets[-1].append(i)
            acc += p.size * p.dtype.itemsize
        self._buckets = buckets

        universal = P(tuple(mesh.axis_names))
        group = self._group
        n_host = self._n
        wire = (mp.dcn_wire if mp.dcn_axis is not None else mp.reduce_dtype)
        wire_ranks = (mesh.shape[mp.dcn_axis]
                      if mp.dcn_axis is not None and mp.dcn_wire is not None
                      else n_host)

        # grads arrive stacked over a leading group axis: the global
        # spelling of "each rank's own local grad"
        self._grad_specs = [P(group, *(s or ())) for s in self._leaf_specs]

        self._init_fns, self._apply_fns = [], []
        self._bucket_pspecs, self._bucket_gspecs = [], []
        self._bucket_state_specs, self._bucket_shardings = [], []
        self._bucket_bytes: List[int] = []
        for idxs in buckets:
            keys = [str(i) for i in idxs]
            pspec = {k: self._leaf_specs[i] for k, i in zip(keys, idxs)}
            gspec = {k: self._grad_specs[i] for k, i in zip(keys, idxs)}
            self._bucket_pspecs.append(pspec)
            self._bucket_gspecs.append(gspec)

            # abstract state: master chunks + inner over them (+ residual)
            master_structs = {
                k: jax.ShapeDtypeStruct(
                    (chunk_size(_prod(self._leaf_local[i]), n_host),),
                    jnp.float32)
                for k, i in zip(keys, idxs)}
            abstract = {
                "master": master_structs,
                "inner": jax.eval_shape(mp.inner.init, master_structs),
            }
            if wire is not None:
                abstract["residual"] = {
                    k: jax.ShapeDtypeStruct(
                        (st.shape[0] * wire_ranks,), jnp.float32)
                    for k, st in master_structs.items()}
            sspecs = jax.tree.map(
                lambda x: universal if getattr(x, "ndim", 0) >= 1 else P(),
                abstract)
            self._bucket_state_specs.append(sspecs)
            self._bucket_shardings.append(jax.tree.map(
                lambda sp: NamedSharding(mesh, sp), sspecs,
                is_leaf=lambda x: isinstance(x, P)))
            self._bucket_bytes.append(sum(
                _prod(x.shape) * x.dtype.itemsize
                for x in jax.tree.leaves(abstract)))

            self._init_fns.append(jax.jit(jax.shard_map(
                self._make_bucket_init(keys, wire is not None, wire_ranks),
                mesh=mesh, in_specs=(pspec,), out_specs=sspecs,
                check_vma=False)))
            self._apply_fns.append(jax.jit(jax.shard_map(
                self._make_bucket_apply(keys),
                mesh=mesh, in_specs=(pspec, gspec, sspecs, P()),
                out_specs=(pspec, sspecs), check_vma=False)))

        scaler0 = _scaler_from_policy(mp.policy, **mp._scaler_kwargs)
        sspec = jax.tree.map(lambda _: P(), scaler0)
        self._phase_a = jax.jit(jax.shard_map(
            self._make_phase_a(), mesh=mesh,
            in_specs=(treedef.unflatten(self._grad_specs), sspec),
            out_specs=(treedef.unflatten(self._grad_specs), P()),
            check_vma=False))
        self._built = True

    # -- traced program bodies ----------------------------------------------
    def _make_phase_a(self):
        mp = self.mp

        def phase_a(scaled_grads, scaler):
            from apex_tpu.parallel import collectives as _coll

            g32, found_inf = scaler.unscale(scaled_grads,
                                            out_dtype=jnp.float32)
            # the skip decision must agree across the whole group before
            # any bucket steps, or the host-side chunks diverge per rank
            found_inf = _coll.pmax(
                found_inf.astype(jnp.float32), mp._zero_group()) > 0
            if self._found_inf_reducer is not None:
                found_inf = self._found_inf_reducer(found_inf)
            return g32, found_inf

        return phase_a

    def _make_bucket_init(self, keys: Sequence[str], with_residual: bool,
                          wire_ranks: int):
        mp = self.mp

        def bucket_init(bp):
            n = mp._zero_group_size()
            idx = mp._zero_group_index()
            master = {k: local_chunk(p.astype(jnp.float32), n, idx)
                      for k, p in bp.items()}
            out = {"master": master, "inner": mp.inner.init(master)}
            if with_residual:
                out["residual"] = {
                    k: jnp.zeros((chunk_size(p.size, n) * wire_ranks,),
                                 jnp.float32)
                    for k, p in bp.items()}
            return out

        return bucket_init

    def _scatter_leaf(self, g, err):
        """(reduced chunk, new residual) — mirrors _apply_zero's wire
        dispatch per leaf (g is this rank's full local grad)."""
        mp = self.mp
        if mp.dcn_axis is not None:
            from apex_tpu.parallel.hierarchy import hier_scatter_chunk

            if mp.dcn_wire is not None:
                return hier_scatter_chunk(
                    g, mp.dcn_axis, mp.zero_axis, wire_dtype=mp.dcn_wire,
                    residual=err)
            return hier_scatter_chunk(g, mp.dcn_axis, mp.zero_axis)[0], err
        if mp.reduce_dtype is not None:
            from apex_tpu.parallel.quantize import quantized_reduce_scatter

            n = mp._zero_group_size()
            return quantized_reduce_scatter(
                g, n, mp.zero_axis, mp.reduce_dtype, residual=err)
        n = mp._zero_group_size()
        return scatter_chunk(g, n, mp.zero_axis), err

    def _gather_leaf(self, c, shape, dtype):
        mp = self.mp
        if mp.dcn_axis is not None:
            from apex_tpu.parallel.hierarchy import hier_gather_chunk

            return hier_gather_chunk(c, shape, dtype, mp.dcn_axis,
                                     mp.zero_axis,
                                     gather_dtype=mp.gather_dtype)
        return gather_leaf(c, shape, dtype, mp.zero_axis,
                           gather_dtype=mp.gather_dtype)

    def _make_bucket_apply(self, keys: Sequence[str]):
        mp = self.mp

        def bucket_apply(bp, bg, st, found_inf):
            n = mp._zero_group_size()
            res = st.get("residual")
            g_chunks, new_err = {}, {}
            for k in keys:
                # drop the stacked group axis: this rank's own grad
                c, e = self._scatter_leaf(
                    bg[k][0], None if res is None else res[k])
                g_chunks[k] = c / n
                new_err[k] = e
            updates, stepped_inner = mp.inner.update(
                g_chunks, st["inner"], st["master"])
            stepped_master = optax.apply_updates(st["master"], updates)
            keep = lambda new, old: jax.tree.map(  # noqa: E731
                lambda a, b: jnp.where(found_inf, b, a), new, old)
            new_master = keep(stepped_master, st["master"])
            out_state = {"master": new_master,
                         "inner": keep(stepped_inner, st["inner"])}
            if res is not None:
                out_state["residual"] = keep(new_err, res)
            new_params = {
                k: self._gather_leaf(new_master[k], bp[k].shape, bp[k].dtype)
                for k in keys}
            return new_params, out_state

        return bucket_apply

    # -- public surface ------------------------------------------------------
    def abstract_step(self, model_params, state: HostOffloadState) -> None:
        """Trace (no compile, no execution) every jitted program of one
        offloaded step — phase A plus each bucket's
        scatter→update→gather — so a surrounding
        ``monitor.comms.comm_accounting`` books the step's full
        collective census: the (hierarchical) grad wire lives in the
        bucket programs and is invisible to a grads-only trace. Journal
        arming (``pretrain_gpt --offload-optimizer --journal``) and the
        pod evidence read their per-tier byte claims off this."""
        if not self._built:
            raise ValueError("call init() before abstract_step: the "
                             "bucket layout derives from the param tree")
        stacked = self._treedef.unflatten([
            jax.ShapeDtypeStruct((self._n,) + tuple(s.shape), s.dtype)
            for s in self._leaf_structs])
        jax.eval_shape(self._phase_a, stacked, state.scaler)
        finf = jax.ShapeDtypeStruct((), jnp.bool_)
        for b, idxs in enumerate(self._buckets):
            bp = {str(i): self._leaf_structs[i] for i in idxs}
            bg = {str(i): jax.ShapeDtypeStruct(
                (self._n,) + tuple(self._leaf_structs[i].shape),
                jnp.float32) for i in idxs}
            st = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                state.host[b])
            jax.eval_shape(self._apply_fns[b], bp, bg, st, finf)

    def init(self, model_params) -> HostOffloadState:
        """Chunk + offload: each bucket's fp32 masters, inner moments, and
        residual land in host RAM; HBM keeps only the scaler."""
        self._build(model_params)
        leaves = jax.tree.leaves(model_params)
        host: List[Dict[str, Any]] = []
        for b, idxs in enumerate(self._buckets):
            bp = {str(i): leaves[i] for i in idxs}
            host.append(jax.device_get(self._init_fns[b](bp)))
        scaler = _scaler_from_policy(self.mp.policy,
                                     **self.mp._scaler_kwargs)
        return HostOffloadState(host, scaler)

    def _put(self, b: int, host_state):
        return jax.device_put(host_state, self._bucket_shardings[b])

    def apply_gradients(self, state: HostOffloadState, model_params,
                        scaled_grads):
        """One offloaded step: phase A (unscale + group overflow pmax),
        then the bucket stream — H2D of bucket ``b+1`` dispatched before
        bucket ``b``'s jitted scatter→update→gather runs, D2H of the
        stepped bucket behind it. Returns ``(new_params, new_state,
        metrics)`` with the same semantics as
        ``MixedPrecisionOptimizer.apply_gradients``."""
        if not self._built:
            raise ValueError("call init() before apply_gradients: the "
                             "bucket layout derives from the param tree")
        tracer = get_tracer()
        g32, found_inf = self._phase_a(scaled_grads, state.scaler)
        p_leaves = jax.tree.leaves(model_params)
        g_leaves = jax.tree.leaves(g32)
        new_leaves: List[Any] = [None] * len(p_leaves)
        new_host: List[Dict[str, Any]] = [None] * len(self._buckets)

        with maybe_span(tracer, "offload.h2d", cat="comm", bucket=0,
                        comm_bytes=self._bucket_bytes[0]):
            placed = self._put(0, state.host[0])
        for b, idxs in enumerate(self._buckets):
            if b + 1 < len(self._buckets):
                # async prefetch: the NEXT bucket's H2D is in flight while
                # this bucket's update runs (_prefetched_zero3_drive's
                # issue-ahead discipline, transfers instead of gathers)
                with maybe_span(tracer, "offload.h2d", cat="comm",
                                bucket=b + 1,
                                comm_bytes=self._bucket_bytes[b + 1]):
                    nxt = self._put(b + 1, state.host[b + 1])
            else:
                nxt = None
            with maybe_span(tracer, "offload.apply", cat="host",
                            bucket=b) as sp:
                bp = {str(i): p_leaves[i] for i in idxs}
                bg = {str(i): g_leaves[i] for i in idxs}
                new_bp, new_st = self._apply_fns[b](
                    bp, bg, placed, found_inf)
                # D2H of the stepped bucket IS the fetch barrier
                new_host[b] = jax.device_get(new_st)
                sp.annotate(d2h_bytes=self._bucket_bytes[b])
            for i in idxs:
                new_leaves[i] = new_bp[str(i)]
            placed = nxt

        new_scaler = state.scaler.update(found_inf)
        metrics = {"found_inf": found_inf,
                   "loss_scale": new_scaler.loss_scale}
        return (self._treedef.unflatten(new_leaves),
                HostOffloadState(new_host, new_scaler), metrics)


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out
