"""FusedAdam — Adam/AdamW as one fused tree update.

Reference: apex/optimizers/fused_adam.py:4-173 (python driver building per-dtype
g/p/m/v lists, :117-170) + csrc/multi_tensor_adam.cu (elementwise update with
``adam_w_mode`` flag and bias correction). Under jit the whole tree update is a
single XLA computation — the fusion the CUDA kernel exists to provide.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers._common import (
    ClassOptimizer,
    cast_like,
    multi_tree_map,
    tree_zeros_like,
)


class FusedAdamState(NamedTuple):
    step: jax.Array  # int32 step count
    exp_avg: optax.Params  # first moment (fp32)
    exp_avg_sq: optax.Params  # second moment (fp32)


def fused_adam(
    lr: float = 1e-3,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    adam_w_mode: bool = True,
    bias_correction: bool = True,
    amsgrad: bool = False,
) -> optax.GradientTransformation:
    """Adam with apex's knobs (fused_adam.py:41-77). ``adam_w_mode=True`` is
    decoupled weight decay (AdamW); False applies L2 into the gradient."""
    if amsgrad:
        raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
    beta1, beta2 = betas

    def init_fn(params):
        return FusedAdamState(
            step=jnp.zeros([], jnp.int32),
            exp_avg=tree_zeros_like(params),
            exp_avg_sq=tree_zeros_like(params),
        )

    def update_fn(grads, state, params=None, *, lr_t=None):
        if params is None:
            raise ValueError("fused_adam requires params")
        step = state.step + 1
        step_lr = jnp.asarray(lr_t if lr_t is not None else lr, jnp.float32)
        if bias_correction:
            bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
            bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        def _upd(g, p, m, v):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if not adam_w_mode and weight_decay != 0.0:
                g32 = g32 + weight_decay * p32
            m_new = beta1 * m + (1.0 - beta1) * g32
            v_new = beta2 * v + (1.0 - beta2) * jnp.square(g32)
            denom = jnp.sqrt(v_new / bc2) + eps
            upd = -step_lr * (m_new / bc1) / denom
            if adam_w_mode and weight_decay != 0.0:
                upd = upd - step_lr * weight_decay * p32
            return upd, m_new, v_new

        updates, new_m, new_v = multi_tree_map(
            _upd, grads, params, state.exp_avg, state.exp_avg_sq, n_out=3
        )
        return cast_like(updates, params), FusedAdamState(step, new_m, new_v)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedAdam(ClassOptimizer):
    def __init__(
        self,
        lr=1e-3,
        bias_correction=True,
        betas=(0.9, 0.999),
        eps=1e-8,
        adam_w_mode=True,
        weight_decay=0.0,
        amsgrad=False,
        **_ignored,
    ):
        super().__init__(
            fused_adam(
                lr=lr,
                betas=betas,
                eps=eps,
                weight_decay=weight_decay,
                adam_w_mode=adam_w_mode,
                bias_correction=bias_correction,
                amsgrad=amsgrad,
            ),
            lr=lr,
        )
