"""Shared plumbing for fused optimizers."""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax


def tree_zeros_like(params, dtype=jnp.float32):
    return jax.tree.map(lambda p: jnp.zeros_like(p, dtype=dtype), params)


def multi_tree_map(fn, *trees, n_out: int):
    """Map ``fn`` over N parallel trees where fn returns an ``n_out``-tuple;
    returns ``n_out`` trees. The structural analog of a multi_tensor kernel
    emitting several output lists (csrc/multi_tensor_apply.cuh works on
    tensor-list tuples). ``n_out`` must be given explicitly so an empty param
    tree (e.g. an optax.masked group) yields empty trees instead of crashing."""
    treedef = jax.tree.structure(trees[0])
    flat_sets = [treedef.flatten_up_to(t) for t in trees]
    results = [fn(*leaves) for leaves in zip(*flat_sets)]
    return tuple(treedef.unflatten([r[i] for r in results]) for i in range(n_out))


def cast_like(updates, params):
    """Emit updates in each param's dtype (state math stays fp32)."""
    return jax.tree.map(lambda u, p: u.astype(p.dtype), updates, params)


class ClassOptimizer:
    """Small adapter giving optax transforms the reference's class spelling.

    ``FusedAdam(lr=...)`` in the reference is a torch Optimizer; here the
    class wraps a ``GradientTransformation`` so both styles work:

        tx = apex_tpu.optimizers.FusedAdam(lr=1e-3)
        state = tx.init(params)
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
    """

    def __init__(self, transform: optax.GradientTransformation, lr: float = None):
        self._tx = transform
        #: The construction-time learning rate, exposed for wrappers that need
        #: it (the reference reads group['lr'] live, e.g. LARC.py:96).
        self.lr = lr

    def init(self, params):
        return self._tx.init(params)

    def update(self, grads, state, params=None, **extra):
        return self._tx.update(grads, state, params, **extra)

    @property
    def transform(self) -> optax.GradientTransformation:
        return self._tx
