"""Shared plumbing for fused optimizers.

Reference: the tensor-list iteration apex repeats per optimizer over
``csrc/multi_tensor_apply.cuh`` (each apex/optimizers/*.py class walks
grouped param/grad/state lists through one fused CUDA launch); here that
pattern is hoisted once as pytree maps — :func:`multi_tree_map` is the
structural analog of a multi-tensor kernel emitting several output lists.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax


def tree_zeros_like(params, dtype=jnp.float32):
    return jax.tree.map(lambda p: jnp.zeros_like(p, dtype=dtype), params)


def tree_sumsq(tree) -> jax.Array:
    """fp32 sum of squares over every float leaf (the first stage of
    ``multi_tensor_l2norm``, csrc/multi_tensor_l2norm_kernel.cu). Shared by
    the sharded-norm paths (ZeRO grad-norm metrics, LAMB's inter-shard
    norms): callers psum the scalar across the shard axis, then sqrt.
    Uses ``tree_l2norm``'s float-leaf filter so the sharded and replicated
    norm semantics cannot drift."""
    from apex_tpu.ops.multi_tensor import _float_leaves

    leaves = _float_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    total = jnp.zeros((), jnp.float32)
    for leaf in leaves:
        total = total + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return total


def sharded_tree_sumsq(tree, axes, extra_axes=None) -> jax.Array:
    """Global fp32 sum of squares of a *sharded* tree, inside shard_map.

    Per-leaf squared partials are psum'd over ``axes`` plus that leaf's
    entry in ``extra_axes`` — a matching pytree whose leaves are tuples of
    the mesh axes the underlying param is SHARDED over — so shards of
    model/pipe-sharded params count exactly once while replicated leaves
    are not double-counted under hybrid meshes. Leaves sharing an axis
    set share one psum. ``extra_axes=None`` reduces every leaf over
    ``axes`` alone (``== collectives.psum(tree_sumsq(tree), axes)``)."""
    from apex_tpu.parallel import collectives

    base = (axes,) if isinstance(axes, str) else tuple(axes)
    g_leaves, treedef = jax.tree.flatten(tree)
    e_leaves = ([()] * len(g_leaves) if extra_axes is None
                else treedef.flatten_up_to(extra_axes))
    by_axes: dict = {}
    for g, extra in zip(g_leaves, e_leaves):
        key = base + tuple(a for a in tuple(extra) if a not in base)
        by_axes.setdefault(key, []).append(g)
    total = jnp.zeros((), jnp.float32)
    for key, leaves in by_axes.items():
        total = total + collectives.psum(tree_sumsq(leaves), key)
    return total


def multi_tree_map(fn, *trees, n_out: int):
    """Map ``fn`` over N parallel trees where fn returns an ``n_out``-tuple;
    returns ``n_out`` trees. The structural analog of a multi_tensor kernel
    emitting several output lists (csrc/multi_tensor_apply.cuh works on
    tensor-list tuples). ``n_out`` must be given explicitly so an empty param
    tree (e.g. an optax.masked group) yields empty trees instead of crashing."""
    treedef = jax.tree.structure(trees[0])
    flat_sets = [treedef.flatten_up_to(t) for t in trees]
    results = [fn(*leaves) for leaves in zip(*flat_sets)]
    return tuple(treedef.unflatten([r[i] for r in results]) for i in range(n_out))


def lamb_leaf_update(
    g32,
    p32,
    m,
    v,
    *,
    beta1,
    beta2,
    beta1_grad,
    bc1,
    bc2,
    eps,
    weight_decay,
    use_nvlamb,
    sumsq: Callable = None,
):
    """Shared per-leaf LAMB math (csrc/multi_tensor_lamb.cu stages 1+2):
    Adam-style moments, bias correction, decoupled weight decay, per-tensor
    trust ratio. Returns ``(trust_scaled_update, m_new, v_new)`` where the
    parameter step is ``p32 - lr * trust_scaled_update``. ``sumsq`` lets
    sharded callers psum squared partials across a mesh axis."""
    if sumsq is None:
        sumsq = lambda x: jnp.sum(jnp.square(x))  # noqa: E731
    m_new = beta1 * m + beta1_grad * g32
    v_new = beta2 * v + (1.0 - beta2) * jnp.square(g32)
    upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if weight_decay != 0.0:
        upd = upd + weight_decay * p32
    w_norm = jnp.sqrt(sumsq(p32))
    u_norm = jnp.sqrt(sumsq(upd))
    ratio = jnp.where(
        (w_norm > 0) & (u_norm > 0), w_norm / u_norm, jnp.asarray(1.0, jnp.float32)
    )
    if weight_decay == 0.0 and not use_nvlamb:
        ratio = jnp.asarray(1.0, jnp.float32)
    return ratio * upd, m_new, v_new


def cast_like(updates, params):
    """Emit updates in each param's dtype (state math stays fp32)."""
    return jax.tree.map(lambda u, p: u.astype(p.dtype), updates, params)


class ClassOptimizer:
    """Small adapter giving optax transforms the reference's class spelling.

    ``FusedAdam(lr=...)`` in the reference is a torch Optimizer; here the
    class wraps a ``GradientTransformation`` so both styles work:

        tx = apex_tpu.optimizers.FusedAdam(lr=1e-3)
        state = tx.init(params)
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
    """

    def __init__(self, transform: optax.GradientTransformation, lr: float = None):
        self._tx = transform
        #: The construction-time learning rate, exposed for wrappers that need
        #: it (the reference reads group['lr'] live, e.g. LARC.py:96).
        self.lr = lr

    def init(self, params):
        return self._tx.init(params)

    def update(self, grads, state, params=None, **extra):
        return self._tx.update(grads, state, params, **extra)

    @property
    def transform(self) -> optax.GradientTransformation:
        return self._tx
