"""FusedAdagrad — Adagrad as one fused tree update.

Reference: apex/optimizers/fused_adagrad.py + csrc/multi_tensor_adagrad.cu
(``h += g^2; p -= lr * g / (sqrt(h) + eps)`` with optional decoupled
``adagrad_w_mode`` weight decay).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers._common import (
    ClassOptimizer,
    cast_like,
    multi_tree_map,
    tree_zeros_like,
)


class FusedAdagradState(NamedTuple):
    step: jax.Array
    sum_sq: optax.Params


def fused_adagrad(
    lr: float = 1e-2,
    eps: float = 1e-10,
    weight_decay: float = 0.0,
    adagrad_w_mode: bool = False,
) -> optax.GradientTransformation:
    def init_fn(params):
        return FusedAdagradState(
            step=jnp.zeros([], jnp.int32), sum_sq=tree_zeros_like(params)
        )

    def update_fn(grads, state, params=None, *, lr_t=None):
        if params is None:
            raise ValueError("fused_adagrad requires params")
        step_lr = jnp.asarray(lr_t if lr_t is not None else lr, jnp.float32)

        def _upd(g, p, h):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay != 0.0 and not adagrad_w_mode:
                g32 = g32 + weight_decay * p32
            h_new = h + jnp.square(g32)
            upd = -step_lr * g32 / (jnp.sqrt(h_new) + eps)
            if weight_decay != 0.0 and adagrad_w_mode:
                upd = upd - step_lr * weight_decay * p32
            return upd, h_new

        updates, new_h = multi_tree_map(_upd, grads, params, state.sum_sq, n_out=2)
        return cast_like(updates, params), FusedAdagradState(state.step + 1, new_h)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedAdagrad(ClassOptimizer):
    def __init__(self, lr=1e-2, eps=1e-10, weight_decay=0.0, adagrad_w_mode=False, **_ignored):
        super().__init__(
            fused_adagrad(lr=lr, eps=eps, weight_decay=weight_decay, adagrad_w_mode=adagrad_w_mode),
            lr=lr,
        )
