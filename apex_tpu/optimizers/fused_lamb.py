"""FusedLAMB — layer-wise adaptive large-batch optimizer.

Reference: apex/optimizers/fused_lamb.py — two-phase step: (1) global grad
norm via ``multi_tensor_l2norm`` (:108-136), (2) ``multi_tensor_lamb``
(csrc/multi_tensor_lamb.cu): Adam-style moments, per-tensor param/update
norms, trust ratio ``||p|| / ||update||``, scaled apply. Knobs preserved:
``bias_correction``, ``grad_averaging``, ``adam_w_mode``, ``max_grad_norm``
(global clip), ``use_nvlamb`` (apply trust ratio even where weight_decay==0).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.ops.multi_tensor import tree_l2norm
from apex_tpu.parallel import collectives
from apex_tpu.optimizers._common import (
    ClassOptimizer,
    cast_like,
    lamb_leaf_update,
    multi_tree_map,
    tree_zeros_like,
)


class FusedLAMBState(NamedTuple):
    step: jax.Array
    exp_avg: optax.Params
    exp_avg_sq: optax.Params


def fused_lamb(
    lr: float = 1e-3,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    bias_correction: bool = True,
    grad_averaging: bool = True,
    adam_w_mode: bool = True,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
    norm_psum_axis: str = None,
) -> optax.GradientTransformation:
    """``norm_psum_axis``: when each leaf is a shard of the true tensor (ZeRO,
    apex_tpu.optimizers.distributed), per-tensor and global norms must sum
    squared partials across that mesh axis — the reference's inter-rank norm
    allreduce in DistributedFusedLAMB."""
    beta1, beta2 = betas

    def _sumsq(x):
        s = jnp.sum(jnp.square(x))
        if norm_psum_axis is not None:
            # scoped verb (parallel/collectives.py): the per-tensor norm
            # psums are real shard-axis traffic the comm accounting and
            # trace-join attribution must see
            s = collectives.psum(s, norm_psum_axis)
        return s
    if not adam_w_mode:
        raise RuntimeError("FusedLAMB only supports adam_w_mode (decoupled wd), as the reference kernel does.")

    def init_fn(params):
        return FusedLAMBState(
            step=jnp.zeros([], jnp.int32),
            exp_avg=tree_zeros_like(params),
            exp_avg_sq=tree_zeros_like(params),
        )

    def update_fn(grads, state, params=None, *, lr_t=None):
        if params is None:
            raise ValueError("fused_lamb requires params")
        step = state.step + 1
        step_lr = jnp.asarray(lr_t if lr_t is not None else lr, jnp.float32)
        beta1_grad = (1.0 - beta1) if grad_averaging else 1.0
        if bias_correction:
            bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
            bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        # Phase 1: global grad norm + clip factor (fused_lamb.py:108-136).
        if norm_psum_axis is not None:
            leaves = [g for g in jax.tree.leaves(grads)]
            global_norm = jnp.sqrt(
                sum(_sumsq(g.astype(jnp.float32)) for g in leaves)
                if leaves else jnp.asarray(0.0, jnp.float32)
            )
        else:
            global_norm = tree_l2norm(grads)
        if max_grad_norm and max_grad_norm > 0:
            clip = jnp.maximum(1.0, global_norm / max_grad_norm)
        else:
            clip = jnp.asarray(1.0, jnp.float32)

        def _upd(g, p, m, v):
            g32 = g.astype(jnp.float32) / clip
            scaled_upd, m_new, v_new = lamb_leaf_update(
                g32,
                p.astype(jnp.float32),
                m,
                v,
                beta1=beta1,
                beta2=beta2,
                beta1_grad=beta1_grad,
                bc1=bc1,
                bc2=bc2,
                eps=eps,
                weight_decay=weight_decay,
                use_nvlamb=use_nvlamb,
                sumsq=_sumsq,
            )
            return (-step_lr * scaled_upd, m_new, v_new)

        updates, new_m, new_v = multi_tree_map(
            _upd, grads, params, state.exp_avg, state.exp_avg_sq, n_out=3
        )
        return cast_like(updates, params), FusedLAMBState(step, new_m, new_v)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedLAMB(ClassOptimizer):
    def __init__(
        self,
        lr=1e-3,
        bias_correction=True,
        betas=(0.9, 0.999),
        eps=1e-6,
        weight_decay=0.01,
        grad_averaging=True,
        adam_w_mode=True,
        max_grad_norm=1.0,
        use_nvlamb=False,
        norm_psum_axis=None,
        **_ignored,
    ):
        # norm_psum_axis: set to the ZeRO shard axis when this transform
        # runs over 1/n chunks (amp.MixedPrecisionOptimizer(zero_axis=...));
        # per-tensor trust-ratio and global-clip norms then sum squared
        # partials across the shards (DistributedFusedLAMB's inter-rank
        # L2-norm allreduce)
        super().__init__(
            fused_lamb(
                lr=lr,
                betas=betas,
                eps=eps,
                weight_decay=weight_decay,
                bias_correction=bias_correction,
                grad_averaging=grad_averaging,
                adam_w_mode=adam_w_mode,
                max_grad_norm=max_grad_norm,
                use_nvlamb=use_nvlamb,
                norm_psum_axis=norm_psum_axis,
            ),
            lr=lr,
        )
