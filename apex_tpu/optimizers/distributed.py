"""ZeRO-style distributed optimizers: reduce-scatter → sharded update → all-gather.

Reference: apex/contrib/optimizers/distributed_fused_adam.py:55-477 and
distributed_fused_lamb.py — flattened params split into blocks/chunks/shards,
backward-hook-driven overlapped reduce-scatter pipelines on dedicated process
groups, a sharded Adam/LAMB step over each rank's shard, then an all-gather of
updated params (``_pipeline_block_reductions`` :397-441, ``_pipeline_step``
:443-477).

TPU-native design: all of the reference's machinery — hooks, block/chunk
bookkeeping, dedicated reduce-scatter/all-reduce process groups, stream
pipelining — exists to overlap communication with eager-mode backward. Under
XLA, overlap is the latency-hiding scheduler's job; what remains is the ZeRO
*math*, which is three collectives:

    grads  --psum_scatter(axis)-->  grad shard        (1/n of every leaf)
    shard  --inner optimizer   -->  update shard      (opt state is 1/n too)
    update --all_gather(axis)  -->  full update tree

``distributed_fused`` wraps ANY fused transform (FusedAdam, FusedSGD, …) this
way; per-leaf chunks are 1-D slices of the flattened leaf, padded to the axis
size. LAMB needs its per-tensor trust-ratio norms summed across shards, so
``fused_lamb`` grows a ``norm_psum_axis`` and ``DistributedFusedLAMB`` passes
it through. The reference's e5m2-compressed allgather option (:64) maps to
``gather_dtype``: the updated chunk is cast (bf16 is the TPU-native choice —
XLA has no sub-byte float collectives) *before* the all-gather, so the
broadcast payload halves while the fp32 masters stay exact.

The chunk helpers (``local_chunk``/``scatter_chunk``/``gather_leaf``) are
public: ``amp.MixedPrecisionOptimizer(zero_axis=...)`` reuses them to run the
whole O2 master/moment state ZeRO-sharded (amp/frontend.py).

Usage (inside shard_map over the ``data`` axis — grads enter *unreduced*,
the scatter IS the gradient reduction, like the reference's hook-driven
reduce-scatter replaces DDP allreduce):

    tx = distributed_fused(fused_adam(lr=1e-3))
    state = tx.init(params)                       # holds 1/n of the moments
    updates, state = tx.update(grads, state, params)
    params = optax.apply_updates(params, updates)

Out-specs for the optimizer state under shard_map: ``state_specs(state,
axis)`` (moment leaves are sharded on the axis; the step scalar replicated).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.monitor.comms import collective_scope as _comm
from apex_tpu.optimizers._common import ClassOptimizer
from apex_tpu.optimizers.fused_adam import fused_adam
from apex_tpu.optimizers.fused_lamb import fused_lamb
from apex_tpu.optimizers.fused_sgd import fused_sgd
from apex_tpu.parallel.mesh import AXIS_DATA


def _padded_size(n_elems: int, n_shards: int) -> int:
    return ((n_elems + n_shards - 1) // n_shards) * n_shards


def chunk_size(n_elems: int, n_shards: int) -> int:
    """Per-shard 1-D chunk length of a leaf with ``n_elems`` elements."""
    return _padded_size(n_elems, n_shards) // n_shards


def _flat_padded(x: jax.Array, n: int) -> jax.Array:
    """Flatten and zero-pad to a multiple of ``n`` — the one place defining
    the chunk layout that slice and scatter must agree on."""
    flat = x.reshape(-1)
    padded = _padded_size(flat.size, n)
    if padded != flat.size:
        flat = jnp.pad(flat, (0, padded - flat.size))
    return flat


def local_chunk(x: jax.Array, n: int, idx) -> jax.Array:
    """This shard's 1-D chunk of a leaf (flatten → zero-pad → slice)."""
    flat = _flat_padded(x, n)
    k = flat.size // n
    return lax.dynamic_slice(flat, (idx * k,), (k,))


def scatter_chunk(x: jax.Array, n: int, axis: str) -> jax.Array:
    """Reduce-scatter a full (replica-partial) leaf into this rank's chunk.

    This IS the data-parallel gradient reduction of the ZeRO step (the
    reference's hook-driven reduce-scatter subsumes DDP allreduce,
    distributed_fused_adam.py:397-441): callers divide by the axis size for
    gradient averaging."""
    flat = _flat_padded(x, n)
    with _comm("psum_scatter", axis, flat):
        return lax.psum_scatter(flat, axis, scatter_dimension=0, tiled=True)


def gather_leaf(
    chunk: jax.Array,
    shape,
    dtype,
    axis: str,
    gather_dtype: Optional[Any] = None,
) -> jax.Array:
    """All-gather chunks back into the full leaf shape.

    The chunk is cast to ``gather_dtype`` (default: the param dtype)
    *before* the collective so a bf16 gather moves half the bytes — the
    role of the reference's e5m2-compressed allgather option
    (distributed_fused_adam.py:64). The comm scope sees the CAST payload,
    so ``monitor.comms`` tallies the gather at its true wire dtype.

    An INTEGER ``gather_dtype`` (int8) goes one notch further: the chunk
    is quantized at a per-chunk fp32 scale (tiny side-channel gather) and
    decoded after the collective (parallel/quantize.py) — 1 B/elem on the
    wire; the fp32 masters stay exact and every rank decodes the same
    view, so ranks cannot diverge."""
    n_elems = 1
    for s in shape:
        n_elems *= s
    if gather_dtype is not None and jnp.issubdtype(
            jnp.dtype(gather_dtype), jnp.integer):
        if jnp.dtype(gather_dtype) != jnp.dtype(jnp.int8):
            raise ValueError(
                f"unsupported integer gather_dtype {gather_dtype!r}: the "
                f"quantized wire is int8 only (parallel/quantize.py)")
        from apex_tpu.parallel.quantize import quantized_gather_chunk

        full = quantized_gather_chunk(
            chunk.astype(jnp.float32), axis, "int8")
        return full[:n_elems].reshape(shape).astype(dtype)
    payload = chunk.astype(gather_dtype if gather_dtype is not None else dtype)
    with _comm("all_gather", axis, payload):
        full = lax.all_gather(payload, axis, axis=0, tiled=True)
    return full[:n_elems].reshape(shape).astype(dtype)


# backward-compat private aliases (pre-ZeRO-frontend spelling)
_local_chunk = local_chunk
_scatter_chunk = scatter_chunk


# ---------------------------------------------------------------------------
# ZeRO-3 layer-stacked chunks (params sharded 1/n with per-layer JIT gather)
# ---------------------------------------------------------------------------
#
# A stacked leaf ``(L, ...)`` (the scan-shaped layer stacks of
# models/_transformer.py) chunks PER ROW into ``(L, k)`` — each row is the
# ``local_chunk`` of that layer's flattened params — so one layer's weights
# can be all-gathered just-in-time inside the layer loop while the rest of
# the model stays sharded (the cross-replica weight sharding of Xu et al.
# extended from the update to the model itself, ROADMAP item 1). Leading-dim
# machinery (pipeline stage shards, vpp interleaving, scan/unroll slicing)
# keeps working on the chunk stack unchanged.


def local_chunk_stacked(x: jax.Array, n: int, idx) -> jax.Array:
    """Per-row 1-D chunks of a stacked leaf: ``(L, ...) -> (L, k)`` where
    row ``i`` is ``local_chunk(x[i], n, idx)`` (same flatten/pad/slice
    layout, so per-row gathers and whole-leaf gathers agree exactly)."""
    L = x.shape[0]
    flat = x.reshape(L, -1)
    padded = _padded_size(flat.shape[1], n)
    if padded != flat.shape[1]:
        flat = jnp.pad(flat, ((0, 0), (0, padded - flat.shape[1])))
    k = padded // n
    return lax.dynamic_slice(flat, (0, idx * k), (L, k))


def gather_stacked_leaf(
    chunk: jax.Array,
    row_shape,
    dtype,
    axis: str,
    gather_dtype: Optional[Any] = None,
) -> jax.Array:
    """All-gather a ``(L, k)`` chunk stack back into ``(L, *row_shape)``.

    The bulk (whole-stack) inverse of :func:`local_chunk_stacked` — used by
    host-side materialization (checkpointing, eval). The hot path gathers
    one ROW at a time via :func:`gather_leaf` inside the layer loop; a
    whole-stack gather in a ZeRO-3 train step is exactly the hazard
    ``lint.trace.zero3_gather_hazards`` flags."""
    if gather_dtype is not None and jnp.issubdtype(
            jnp.dtype(gather_dtype), jnp.integer):
        raise ValueError(
            "integer gather_dtype (the quantized int8 wire) is per-LEAF "
            "only (gather_leaf routes it through parallel/quantize.py); a "
            "bare astype here would truncate the weights — bulk stacked "
            "gathers are host-side materialization paths and stay exact")
    L = chunk.shape[0]
    payload = chunk.astype(gather_dtype if gather_dtype is not None else dtype)
    with _comm("all_gather", axis, payload):
        full = lax.all_gather(payload, axis, axis=1, tiled=True)
    n_elems = 1
    for s in row_shape:
        n_elems *= s
    return (full[:, :n_elems]
            .reshape((L,) + tuple(row_shape)).astype(dtype))


class ChunkedMeta(NamedTuple):
    """Static gather metadata for a ZeRO-3 chunk tree.

    ``shapes`` mirrors the chunk tree: each leaf a ``ShapeDtypeStruct``
    holding the LOCAL (per-device, TP/pipe-divided) full shape the chunk
    gathers back to — the per-LAYER row shape for stacked leaves, the whole
    leaf shape otherwise. ``axis`` is the ZeRO mesh axis; ``gather_dtype``
    the wire dtype of the JIT gathers (None = each leaf's own dtype)."""

    shapes: Any
    axis: str
    gather_dtype: Optional[Any] = None

    def subtree(self, key) -> "ChunkedMeta":
        return self._replace(shapes=self.shapes[key])

    def select(self, keys) -> "ChunkedMeta":
        return self._replace(
            shapes={k: v for k, v in self.shapes.items() if k in keys})


def gather_chunked_tree(chunks: Any, meta: ChunkedMeta) -> Any:
    """Just-in-time all-gather of a (flat-leaf) chunk tree back to full
    local arrays — one collective per leaf, each at the wire dtype. Under
    AD the all_gather transposes to a psum_scatter, so the gradient of a
    gathered param comes back as an ALREADY data-axis-reduced chunk (the
    per-layer reduce-scatter of the ZeRO-3 step, for free)."""
    return jax.tree.map(
        lambda c, s: gather_leaf(c, s.shape, s.dtype, meta.axis,
                                 gather_dtype=meta.gather_dtype),
        chunks, meta.shapes,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))


def distributed_fused(
    inner: optax.GradientTransformation,
    axis: str = AXIS_DATA,
    *,
    grad_average: bool = True,
    gather_dtype: Optional[Any] = None,
) -> optax.GradientTransformation:
    """Wrap a fused transform with ZeRO sharding over a mesh axis.

    Must run inside shard_map binding ``axis``. ``update`` expects the
    *unreduced* per-replica gradient tree (the psum_scatter performs the
    data-parallel reduction, like the reference's reduce-scatter pipeline
    subsumes DDP allreduce); ``grad_average=True`` divides by the axis size
    (gradient averaging, distributed_fused_adam.py predivide semantics).
    ``gather_dtype`` compresses the update all-gather's payload (the
    reference's e5m2 allgather knob, :64); the update is still applied in
    each param's own dtype.
    """

    def init_fn(params):
        n = lax.axis_size(axis)
        idx = lax.axis_index(axis)
        chunks = jax.tree.map(
            lambda p: local_chunk(p.astype(jnp.float32), n, idx), params
        )
        return inner.init(chunks)

    def update_fn(grads, state, params=None, **extra):
        if params is None:
            raise ValueError("distributed_fused requires params")
        n = lax.axis_size(axis)
        idx = lax.axis_index(axis)
        g_chunks = jax.tree.map(
            lambda g: scatter_chunk(g.astype(jnp.float32), n, axis)
            / (n if grad_average else 1),
            grads,
        )
        p_chunks = jax.tree.map(
            lambda p: local_chunk(p.astype(jnp.float32), n, idx), params
        )
        upd_chunks, new_state = inner.update(g_chunks, state, p_chunks, **extra)
        updates = jax.tree.map(
            lambda u, p: gather_leaf(u, p.shape, p.dtype, axis,
                                     gather_dtype=gather_dtype),
            upd_chunks,
            params,
        )
        return updates, new_state

    return optax.GradientTransformation(init_fn, update_fn)


def state_specs(state: Any, axis: Any = AXIS_DATA) -> Any:
    """shard_map out-specs for a ZeRO-sharded optimizer state.

    Recurses through arbitrarily nested states — named tuples, chained
    transforms (``optax.chain`` returns a tuple of per-transform states),
    dicts — and marks exactly the 1-D leaves as sharded on ``axis``:
    chunks are 1-D *by construction* (``local_chunk`` flattens), so any
    scalar (step counters) or higher-rank leaf a nested inner transform
    carries is replicated rather than silently mis-sharded. ``axis`` may
    be a tuple of mesh axis names: chunks of model-sharded params differ
    across every axis, so the universal per-device spec is
    ``P(tuple(mesh.axis_names))`` (amp/frontend.py's ZeRO path).
    """
    spec = P(tuple(axis) if isinstance(axis, (tuple, list)) else axis)
    return jax.tree.map(
        lambda x: spec if getattr(x, "ndim", 0) == 1 else P(), state
    )


def sharded_state_shapes(
    inner: optax.GradientTransformation, params: Any, n_shards: int
) -> Any:
    """ShapeDtypeStruct pytree of a ``distributed_fused(inner)`` state as seen
    per device — for building shard_map out_specs (with ``state_specs``)
    without binding the mesh axis. Handles any nesting the inner transform's
    ``init`` produces (chained/named-tuple states included): the abstract
    chunk tree is fed through the real ``inner.init`` under ``eval_shape``."""

    def fake_init(p):
        chunks = jax.tree.map(
            lambda x: jnp.zeros((chunk_size(x.size, n_shards),), jnp.float32),
            p,
        )
        return inner.init(chunks)

    return jax.eval_shape(fake_init, params)


#: pre-r8 name of :func:`sharded_state_shapes`
abstract_state = sharded_state_shapes


class DistributedFusedAdam(ClassOptimizer):
    """ZeRO-sharded FusedAdam (distributed_fused_adam.py:55-477 equivalent).

    The reference's dwu_num_blocks/chunks/rs_pg/ar_pg overlap knobs have no
    TPU meaning (XLA schedules the collectives); the optimizer math and the
    1/n state memory footprint are preserved.
    """

    def __init__(
        self,
        lr=1e-3,
        bias_correction=True,
        betas=(0.9, 0.999),
        eps=1e-8,
        adam_w_mode=True,
        weight_decay=0.0,
        axis: str = AXIS_DATA,
        grad_average: bool = True,
        gather_dtype: Optional[Any] = None,
        **_ignored,
    ):
        super().__init__(
            distributed_fused(
                fused_adam(
                    lr=lr,
                    betas=betas,
                    eps=eps,
                    weight_decay=weight_decay,
                    adam_w_mode=adam_w_mode,
                    bias_correction=bias_correction,
                ),
                axis=axis,
                grad_average=grad_average,
                gather_dtype=gather_dtype,
            ),
            lr=lr,
        )


class DistributedFusedLAMB(ClassOptimizer):
    """ZeRO-sharded FusedLAMB (distributed_fused_lamb.py equivalent).

    Per-tensor trust-ratio norms and the global grad norm are psum'd over the
    shard axis (the reference's inter-rank L2-norm allreduce,
    distributed_fused_lamb.py `_pipeline_step` norm phase).
    """

    def __init__(
        self,
        lr=1e-3,
        bias_correction=True,
        betas=(0.9, 0.999),
        eps=1e-6,
        weight_decay=0.01,
        grad_averaging=True,
        adam_w_mode=True,
        max_grad_norm=1.0,
        use_nvlamb=False,
        axis: str = AXIS_DATA,
        grad_average: bool = True,
        gather_dtype: Optional[Any] = None,
        **_ignored,
    ):
        super().__init__(
            distributed_fused(
                fused_lamb(
                    lr=lr,
                    betas=betas,
                    eps=eps,
                    weight_decay=weight_decay,
                    bias_correction=bias_correction,
                    grad_averaging=grad_averaging,
                    adam_w_mode=adam_w_mode,
                    max_grad_norm=max_grad_norm,
                    use_nvlamb=use_nvlamb,
                    norm_psum_axis=axis,
                ),
                axis=axis,
                grad_average=grad_average,
                gather_dtype=gather_dtype,
            ),
            lr=lr,
        )


class DistributedFusedSGD(ClassOptimizer):
    """ZeRO-sharded FusedSGD (momentum state sharded 1/n)."""

    def __init__(
        self,
        lr=1e-3,
        momentum=0.0,
        dampening=0.0,
        weight_decay=0.0,
        nesterov=False,
        axis: str = AXIS_DATA,
        grad_average: bool = True,
        **_ignored,
    ):
        super().__init__(
            distributed_fused(
                fused_sgd(
                    lr=lr,
                    momentum=momentum,
                    dampening=dampening,
                    weight_decay=weight_decay,
                    nesterov=nesterov,
                ),
                axis=axis,
                grad_average=grad_average,
            ),
            lr=lr,
        )
