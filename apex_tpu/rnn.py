"""RNN / LSTM / GRU / mLSTM (reference: apex/RNN — deprecated there, kept
for API completeness).

The reference reimplements fused-dropout RNN stacks in pure python
(RNN/models.py:19-52, RNNBackend.py:25-232, cells.py:12-55). TPU-native, the
time loop is a ``lax.scan`` (one traced step body, compile time O(1) in
sequence length) and the per-gate GEMMs are packed into one matmul per input
so the MXU sees a single large contraction per step.

Functional API: ``cell = LSTMCell(input_size, hidden)``;
``params = cell.init(key)``; ``RNN([cell, ...]).apply(params_list, x)`` with
``x: (batch, time, input)`` → ``(output, final_states)``.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.utils.nn import inverted_dropout

Params = Dict[str, Any]


class _Cell:
    """Shared packed-GEMM cell plumbing. ``n_gates`` linear blocks of size
    ``hidden`` computed as one (input+hidden) x (n_gates*hidden) matmul."""

    n_gates = 1

    def __init__(self, input_size: int, hidden_size: int, bias: bool = True):
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.bias = bias

    def init(self, key: jax.Array, dtype=jnp.float32) -> Params:
        k1, k2 = jax.random.split(key)
        bound = 1.0 / math.sqrt(self.hidden_size)
        shape_i = (self.input_size, self.n_gates * self.hidden_size)
        shape_h = (self.hidden_size, self.n_gates * self.hidden_size)
        p = {
            "w_ih": jax.random.uniform(k1, shape_i, dtype, -bound, bound),
            "w_hh": jax.random.uniform(k2, shape_h, dtype, -bound, bound),
        }
        if self.bias:
            p["b"] = jnp.zeros((self.n_gates * self.hidden_size,), dtype)
        return p

    def initial_state(self, batch: int, dtype=jnp.float32):
        return jnp.zeros((batch, self.hidden_size), dtype)

    def _gates(self, p: Params, x, h):
        z = x @ p["w_ih"] + h @ p["w_hh"]
        if self.bias:
            z = z + p["b"]
        return z

    def __call__(self, p: Params, state, x):
        raise NotImplementedError


class RNNReLUCell(_Cell):
    """h' = relu(W x + U h + b) (cells.py RNNReLUCell)."""

    def __call__(self, p, h, x):
        return jax.nn.relu(self._gates(p, x, h))


class RNNTanhCell(_Cell):
    def __call__(self, p, h, x):
        return jnp.tanh(self._gates(p, x, h))


class LSTMCell(_Cell):
    """Standard LSTM (i, f, g, o gate order; RNNBackend LSTMCell)."""

    n_gates = 4

    def initial_state(self, batch, dtype=jnp.float32):
        z = jnp.zeros((batch, self.hidden_size), dtype)
        return (z, z)

    def __call__(self, p, state, x):
        h, c = state
        i, f, g, o = jnp.split(self._gates(p, x, h), 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c)


class GRUCell(_Cell):
    """GRU (r, z, n gates; cells.py GRUCell). The candidate gate applies the
    reset to the hidden projection, so it gets its own GEMM block."""

    n_gates = 3

    def __call__(self, p, h, x):
        zi = x @ p["w_ih"]
        zh = h @ p["w_hh"]
        if self.bias:
            zi = zi + p["b"]
        ri, zi_g, ni = jnp.split(zi, 3, axis=-1)
        rh, zh_g, nh = jnp.split(zh, 3, axis=-1)
        r = jax.nn.sigmoid(ri + rh)
        z = jax.nn.sigmoid(zi_g + zh_g)
        n = jnp.tanh(ni + r * nh)
        return (1.0 - z) * n + z * h


class mLSTMCell(LSTMCell):
    """Multiplicative LSTM (cells.py:12-55): the hidden state is modulated by
    ``m = (W_mx x) * (W_mh h)`` before the gate GEMM."""

    def init(self, key, dtype=jnp.float32):
        k1, k2, k3 = jax.random.split(key, 3)
        p = super().init(k1, dtype)
        bound = 1.0 / math.sqrt(self.hidden_size)
        p["w_mx"] = jax.random.uniform(
            k2, (self.input_size, self.hidden_size), dtype, -bound, bound)
        p["w_mh"] = jax.random.uniform(
            k3, (self.hidden_size, self.hidden_size), dtype, -bound, bound)
        return p

    def __call__(self, p, state, x):
        h, c = state
        m = (x @ p["w_mx"]) * (h @ p["w_mh"])
        i, f, g, o = jnp.split(self._gates(p, x, m), 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c)


def _cell_output(state):
    return state[0] if isinstance(state, tuple) else state


class RNN:
    """Stacked multi-layer runner (RNN/models.py:19-52 ``toRNNBackend``).

    ``apply(params_list, x, initial_states=None, dropout_key=None)`` scans
    each layer over time, with inter-layer dropout like the reference's
    ``dropout`` arg.
    """

    def __init__(self, cells: Sequence[_Cell], dropout: float = 0.0):
        self.cells = list(cells)
        self.dropout = dropout

    def init(self, key: jax.Array, dtype=jnp.float32) -> List[Params]:
        keys = jax.random.split(key, len(self.cells))
        return [c.init(k, dtype) for c, k in zip(self.cells, keys)]

    def apply(
        self,
        params: Sequence[Params],
        x: jax.Array,
        initial_states: Optional[List[Any]] = None,
        dropout_key: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, List[Any]]:
        batch = x.shape[0]
        states = initial_states or [
            c.initial_state(batch, x.dtype) for c in self.cells
        ]
        finals = []
        h_seq = x
        for li, (cell, p) in enumerate(zip(self.cells, params)):
            def step(state, xt, cell=cell, p=p):
                new = cell(p, state, xt)
                return new, _cell_output(new)

            final, ys = lax.scan(step, states[li], jnp.swapaxes(h_seq, 0, 1))
            h_seq = jnp.swapaxes(ys, 0, 1)
            finals.append(final)
            if (
                dropout_key is not None
                and self.dropout > 0.0
                and li < len(self.cells) - 1
            ):
                dropout_key, sub = jax.random.split(dropout_key)
                h_seq = inverted_dropout(h_seq, sub, self.dropout)
        return h_seq, finals


def make_lstm(input_size, hidden_size, num_layers=1, bias=True, dropout=0.0) -> RNN:
    """models.py LSTM factory."""
    cells = [
        LSTMCell(input_size if i == 0 else hidden_size, hidden_size, bias)
        for i in range(num_layers)
    ]
    return RNN(cells, dropout)


def make_gru(input_size, hidden_size, num_layers=1, bias=True, dropout=0.0) -> RNN:
    cells = [
        GRUCell(input_size if i == 0 else hidden_size, hidden_size, bias)
        for i in range(num_layers)
    ]
    return RNN(cells, dropout)
